"""Aux subsystems of the sim backend: checkpoint/resume, monitor, metadata.

Parity anchors: the monitor mirrors the reference's MBeans
(MembershipProtocolImpl.java:720-791, ClusterImpl.java:434-469); metadata
versioning mirrors updateIncarnation-on-metadata-change
(ClusterImpl.java:365-369); checkpointing is the SURVEY.md §5 extension
(the reference itself keeps no durable state).
"""

import jax.numpy as jnp

from scalecube_cluster_tpu.ops.merge import decode_incarnation
from scalecube_cluster_tpu.sim import (
    FaultPlan,
    SimParams,
    cluster_summary,
    init_full_view,
    kill,
    load_checkpoint,
    node_view,
    run_ticks,
    save_checkpoint,
    update_metadata,
)
from scalecube_cluster_tpu.sim.state import seeds_mask
from tests.test_sim import small_params


def test_checkpoint_roundtrip_is_exact(tmp_path):
    n = 16
    p = small_params(n)
    plan, sm = FaultPlan.clean(n).with_loss(10.0), seeds_mask(n, [0])
    st = init_full_view(n, user_gossip_slots=2, seed=3)
    st, _ = run_ticks(p, st, plan, sm, 20)

    save_checkpoint(tmp_path / "snap.npz", st, p)
    loaded, p2 = load_checkpoint(tmp_path / "snap.npz")
    assert p2 == p

    # Resume must continue bit-for-bit where the original run continues.
    cont_a, tr_a = run_ticks(p, st, plan, sm, 30)
    cont_b, tr_b = run_ticks(p2, loaded, plan, sm, 30)
    assert bool(jnp.all(cont_a.view == cont_b.view))
    assert bool(jnp.all(tr_a["convergence"] == tr_b["convergence"]))


def test_checkpoint_backfills_derived_fields(tmp_path):
    """Snapshots from before rows/known_cnt existed load via reconstruction
    (they are pure functions of view/rumor_age + params)."""
    import numpy as np

    n = 16
    p = small_params(n)
    plan, sm = FaultPlan.clean(n).with_loss(10.0), seeds_mask(n, [0])
    st = init_full_view(n, user_gossip_slots=2, seed=3)
    st, _ = run_ticks(p, st, plan, sm, 20)
    save_checkpoint(tmp_path / "snap.npz", st, p)

    # Strip the derived fields, as an old-format archive would lack them.
    with np.load(tmp_path / "snap.npz") as data:
        stripped = {
            k: data[k] for k in data.files if k not in ("rows", "known_cnt")
        }
    np.savez(tmp_path / "old.npz", **stripped)

    loaded, _ = load_checkpoint(tmp_path / "old.npz")
    assert bool(jnp.all(loaded.rows == st.rows))
    assert bool(jnp.all(loaded.known_cnt == st.known_cnt))


def test_monitor_views():
    n = 10
    p = small_params(n)
    st = kill(init_full_view(n, user_gossip_slots=2), 7)
    st, _ = run_ticks(
        p, st, FaultPlan.clean(n), seeds_mask(n, [0]), p.suspicion_ticks + 40
    )

    nv = node_view(st, 0)
    assert 7 not in nv.alive_members
    assert 7 in nv.dead_members or 7 in nv.unknown_members
    assert len(nv.alive_members) == n - 2  # everyone else except self and 7

    summary = cluster_summary(st)
    assert summary["n_alive_processes"] == n - 1
    assert summary["viewed_suspect_total"] == 0
    assert summary["tick"] == int(st.tick)


def test_update_metadata_propagates_version():
    """A metadata change bumps the member's incarnation, and every peer learns
    the new version via gossip (updateIncarnation semantics)."""
    n = 12
    p = small_params(n)
    plan, sm = FaultPlan.clean(n), seeds_mask(n, [0])
    st = init_full_view(n, user_gossip_slots=2)
    assert int(st.inc_self[4]) == 0

    st = update_metadata(st, 4)
    assert int(st.inc_self[4]) == 1
    st, _ = run_ticks(p, st, plan, sm, p.periods_to_spread + 4)

    # Every live viewer now holds version 1 of member 4's record.
    versions = decode_incarnation(st.view)[:, 4]
    assert bool(jnp.all(versions == 1))


def test_user_gossip_slot_lifecycle_recycles():
    """A slot sweeps after periods_to_sweep and is reusable for a fresh
    spread — many injections cycle through the same 2 slots (round-1 verdict
    item 8; sweepGossips, GossipProtocolImpl.java:281-304)."""
    from scalecube_cluster_tpu.sim import inject_gossip, user_gossip_swept

    n = 24
    p = small_params(n, periods_to_spread=8, periods_to_sweep=18)
    plan, sm = FaultPlan.clean(n), seeds_mask(n, [0])
    st = init_full_view(n, user_gossip_slots=2, seed=5)

    for round_idx in range(3):  # 3 generations through the same slot
        origin = (7 * round_idx) % n
        st = inject_gossip(st, origin, 0)
        assert not user_gossip_swept(st, origin, 0)
        st, tr = run_ticks(
            p, st, plan, sm, p.periods_to_sweep + p.periods_to_spread + 4
        )
        # Full dissemination happened within the window...
        assert float(jnp.max(tr["gossip_coverage"][:, 0])) == 1.0
        # ...and by now every copy aged out: the slot is recycled everywhere,
        # completing the origin's spread() future.
        assert user_gossip_swept(st, origin, 0)
        assert not bool(jnp.any(st.useen[:, 0]))
