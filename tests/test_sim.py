"""Scenario tests for the TPU sim backend (sim/ + ops/ + parallel/).

These mirror the reference's distributed suites on the array engine with
virtual time — each reference wall-clock scenario becomes a tick-indexed
assertion (SURVEY.md §4 "weakness to inherit-and-fix"):

- MembershipProtocolTest.java:69-91    -> test_cold_join_converges
- MembershipProtocolTest.java:321-371  -> test_kill_suspect_then_dead
- FailureDetectorTest.java:117-146     -> test_lossy_network_no_false_deaths
- MembershipProtocolTest.java:94-263   -> test_partition_and_heal
- MembershipProtocolTest.java:454-520  -> test_restart_new_epoch
- ClusterTest.java:358-399             -> test_graceful_leave
- GossipProtocolTest.java:154-173      -> test_user_gossip_dissemination
- threading model (§1)                 -> test_determinism, test_sharded_equals_single
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from scalecube_cluster_tpu.ops.merge import decode_epoch, decode_status
from scalecube_cluster_tpu.parallel import make_mesh, shard_plan, shard_state
from scalecube_cluster_tpu.sim import (
    FaultPlan,
    SimParams,
    init_full_view,
    init_seeded,
    inject_gossip,
    kill,
    restart,
    run_ticks,
)
from scalecube_cluster_tpu.sim.state import leave, seeds_mask

ALIVE, SUSPECT, DEAD = 0, 1, 2


def small_params(n, **kw):
    """Fast test constants: short sync period so join/heal paths are quick."""
    base = dict(
        n=n,
        gossip_fanout=3,
        periods_to_spread=8,
        periods_to_sweep=18,
        fd_period_ticks=2,
        sync_period_ticks=10,
        suspicion_ticks=30,
        ping_req_members=2,
        user_gossip_slots=2,
    )
    base.update(kw)
    return SimParams(**base)


def statuses(state):
    return decode_status(state.view)


def test_cold_join_converges():
    n = 16
    p = small_params(n)
    st = init_seeded(n, [0], user_gossip_slots=2)
    st, tr = run_ticks(p, st, FaultPlan.clean(n), seeds_mask(n, [0]), 60)
    assert float(tr["convergence"][-1]) == 1.0
    # Everyone sees everyone ALIVE at epoch 0.
    assert bool(jnp.all(statuses(st) == ALIVE))


def test_kill_suspect_then_dead():
    n = 12
    p = small_params(n)
    st = init_full_view(n, user_gossip_slots=2)
    st = kill(st, 5)
    plan, sm = FaultPlan.clean(n), seeds_mask(n, [0])

    # Within a few FD periods every live node suspects 5 (direct probes fail,
    # relays can't reach a dead process either) — but nobody is dead yet.
    st, _ = run_ticks(p, st, plan, sm, p.fd_period_ticks * 4 + p.periods_to_spread)
    live = st.alive
    col5 = statuses(st)[:, 5]
    assert bool(jnp.all(jnp.where(live, col5 == SUSPECT, True)))

    # After the suspicion timeout, DEAD (then tombstone-expired to UNKNOWN).
    st, tr = run_ticks(p, st, plan, sm, p.suspicion_ticks + 10)
    col5 = statuses(st)[:, 5]
    assert bool(jnp.all(jnp.where(live, (col5 == DEAD) | (col5 == 3), True)))
    assert float(tr["convergence"][-1]) == 1.0


def test_lossy_network_no_false_deaths():
    n = 32
    p = small_params(n, suspicion_ticks=40, ping_req_members=3)
    st = init_full_view(n, user_gossip_slots=2)
    plan = FaultPlan.clean(n).with_loss(20.0)
    st, tr = run_ticks(p, st, plan, seeds_mask(n, [0]), 250)
    s = statuses(st)
    false_dead = jnp.sum((s == DEAD) & st.alive[None, :])
    assert int(false_dead) == 0
    # Refutation must have fired under this much loss.
    assert int(st.inc_self.max()) > 0
    assert float(tr["convergence"][-1]) > 0.85


def test_partition_and_heal():
    n = 10
    p = small_params(n)
    st = init_full_view(n, user_gossip_slots=2)
    sm = seeds_mask(n, [0])
    side_a, side_b = list(range(5)), list(range(5, 10))
    cut = FaultPlan.clean(n).partition(side_a, side_b)

    # Partition long enough for suspicion timeouts: each side declares the
    # other DEAD (suspicion-timeout removal, MembershipProtocolTest.java:321-371).
    st, _ = run_ticks(p, st, cut, sm, p.suspicion_ticks + p.fd_period_ticks * 6 + 20)
    s = statuses(st)
    cross = s[jnp.asarray(side_a)][:, jnp.asarray(side_b)]
    assert bool(jnp.all((cross == DEAD) | (cross == 3)))

    # Heal: SYNC anti-entropy (to the seed) re-introduces both sides
    # (README.md:16-17 — SYNC heals partitions).
    st, tr = run_ticks(p, st, FaultPlan.clean(n), sm, 250)
    assert float(tr["convergence"][-1]) == 1.0
    assert bool(jnp.all(statuses(st) == ALIVE))


def test_restart_new_epoch():
    n = 8
    p = small_params(n)
    sm = seeds_mask(n, [0])
    plan = FaultPlan.clean(n)
    st = init_full_view(n, user_gossip_slots=2)
    st = kill(st, 3)
    st, _ = run_ticks(p, st, plan, sm, p.suspicion_ticks + 40)

    st = restart(st, 3)
    st, tr = run_ticks(p, st, plan, sm, 200)
    assert float(tr["convergence"][-1]) == 1.0
    # Everyone sees node 3 ALIVE at its new epoch.
    assert bool(jnp.all(decode_epoch(st.view)[:, 3] == 1))
    assert bool(jnp.all(statuses(st)[:, 3] == ALIVE))


def test_restart_detected_gone_by_fd():
    """A restarted process answers probes with a new identity — DEST_GONE
    (PingData.java:17-22) kills the old record without waiting out suspicion."""
    n = 8
    p = small_params(n, suspicion_ticks=10_000)  # suspicion can't help here
    sm = seeds_mask(n, [0])
    plan = FaultPlan.clean(n)
    st = init_full_view(n, user_gossip_slots=2)
    st = restart(st, 3)  # instant restart: process up, epoch bumped
    st, tr = run_ticks(p, st, plan, sm, 200)
    assert bool(jnp.all(decode_epoch(st.view)[:, 3] == 1))
    assert float(tr["convergence"][-1]) == 1.0


def test_graceful_leave():
    n = 8
    p = small_params(n)
    sm = seeds_mask(n, [0])
    plan = FaultPlan.clean(n)
    st = init_full_view(n, user_gossip_slots=2)
    st = leave(st, 2)
    st, _ = run_ticks(p, st, plan, sm, 3)  # leave gossip rides normal spread
    st = kill(st, 2)
    st, _ = run_ticks(p, st, plan, sm, p.periods_to_spread)
    s = statuses(st)[:, 2]
    live = st.alive
    # Leavers are seen DEAD well before any suspicion timeout could fire.
    assert bool(jnp.all(jnp.where(live, (s == DEAD) | (s == 3), True)))


def test_user_gossip_dissemination():
    n = 50
    p = small_params(n, periods_to_spread=18, periods_to_sweep=38)
    st = init_full_view(n, user_gossip_slots=2)
    st = inject_gossip(st, 7, 0)
    st, tr = run_ticks(p, st, FaultPlan.clean(n), seeds_mask(n, [0]), 30)
    cov = tr["gossip_coverage"][:, 0]
    assert float(cov[-1]) == 1.0
    # Dissemination beats the sweep deadline (GossipProtocolTest.java:154-173).
    full_at = int(jnp.argmax(cov >= 1.0))
    assert full_at <= p.periods_to_sweep


def test_user_gossip_under_loss():
    n = 50
    p = small_params(n, periods_to_spread=18, periods_to_sweep=38)
    st = init_full_view(n, user_gossip_slots=2)
    st = inject_gossip(st, 0, 1)
    plan = FaultPlan.clean(n).with_loss(50.0)
    st, tr = run_ticks(p, st, plan, seeds_mask(n, [0]), 40)
    # The reference's worst tested grid: N=50, 50% loss still disseminates
    # (GossipProtocolTest.java:48-64). Peak coverage (not the final tick):
    # the 40-tick run crosses the sweep deadline, after which early-infected
    # slots recycle and leave the coverage count.
    assert float(jnp.max(tr["gossip_coverage"][:, 1])) == 1.0


def test_delay_below_deadline_harmless_above_fatal():
    """FailureDetectorTest.java:149-177: mean delay well under the ping
    deadline leaves everyone ALIVE; delay far beyond it makes probe round
    trips miss their timer and drives SUSPECT verdicts."""
    n = 12
    # ping_timeout 500ms (LAN default): mild 20ms mean delay never misses.
    p = small_params(n, suspicion_ticks=10_000)  # isolate FD verdicts
    sm = seeds_mask(n, [0])

    mild = FaultPlan.clean(n).with_mean_delay(20.0)
    st = init_full_view(n, user_gossip_slots=2)
    st, tr = run_ticks(p, st, mild, sm, 80)
    assert int(tr["n_suspected"][-1]) == 0

    # Erlang-2 tail at x=500/2000: ~97% of ping round trips miss the timer.
    heavy = FaultPlan.clean(n).with_mean_delay(2000.0)
    st = init_full_view(n, user_gossip_slots=2)
    st, tr = run_ticks(p, st, heavy, sm, 80)
    assert int(tr["n_suspected"][-1]) > n  # widespread missed deadlines
    # ...but gossip (no deadline) still disseminates fine. Peak coverage:
    # the 25-tick window crosses the sweep deadline (18), after which
    # early-infected slots recycle out of the coverage count.
    st = inject_gossip(st, 0, 0)
    st, tr = run_ticks(p, st, heavy, sm, 25)
    assert float(jnp.max(tr["gossip_coverage"][:, 0])) == 1.0


def test_determinism():
    n = 16
    p = small_params(n)
    plan, sm = FaultPlan.clean(n).with_loss(10.0), seeds_mask(n, [0])
    outs = []
    for _ in range(2):
        st = init_seeded(n, [0], user_gossip_slots=2, seed=42)
        st, tr = run_ticks(p, st, plan, sm, 50)
        outs.append((st.view, tr["convergence"]))
    assert bool(jnp.all(outs[0][0] == outs[1][0]))
    assert bool(jnp.all(outs[0][1] == outs[1][1]))


def test_uniform_plan_equals_dense_plan():
    """The compact [1,1] FaultPlan (O(1) memory, sim/faults.py) drives the
    exact same trajectory as its dense equivalent — same loss draws, same
    convergence (the big-n benchmark correctness precondition)."""
    n = 16
    p = small_params(n)
    sm = seeds_mask(n, [0])
    outs = []
    for plan in (
        FaultPlan.clean(n).with_loss(10.0).with_mean_delay(100.0),
        FaultPlan.uniform(loss_percent=10.0, mean_delay_ms=100.0),
    ):
        st = init_full_view(n, user_gossip_slots=2, seed=7)
        st = kill(st, 3)
        st, tr = run_ticks(p, st, plan, sm, 50)
        outs.append((st.view, tr["convergence"]))
    assert bool(jnp.all(outs[0][0] == outs[1][0]))
    assert bool(jnp.all(outs[0][1] == outs[1][1]))


@pytest.mark.parametrize("n_dev", [8])
def test_sharded_equals_single(n_dev):
    """Sharding the member axis over 8 virtual devices must not change the
    computation — same seed, same trajectory, bit-for-bit."""
    assert len(jax.devices()) >= n_dev
    n = 32
    p = small_params(n)
    plan, sm = FaultPlan.clean(n).with_loss(15.0), seeds_mask(n, [0])

    st_single = init_full_view(n, user_gossip_slots=2, seed=7)
    st_single = kill(st_single, 4)
    ref, tr_ref = run_ticks(p, st_single, plan, sm, 80)

    mesh = make_mesh(jax.devices()[:n_dev])
    st_sh = shard_state(kill(init_full_view(n, user_gossip_slots=2, seed=7), 4), mesh)
    plan_sh = shard_plan(plan, mesh)
    out, tr_sh = run_ticks(p, st_sh, plan_sh, sm, 80)

    assert bool(jnp.all(jax.device_get(out.view) == jax.device_get(ref.view)))
    assert bool(
        jnp.all(jax.device_get(tr_sh["convergence"]) == jax.device_get(tr_ref["convergence"]))
    )


def test_diagonal_invariant():
    """A live node never believes itself SUSPECT/DEAD (self-refutation)."""
    n = 16
    p = small_params(n)
    st = init_full_view(n, user_gossip_slots=2)
    plan = FaultPlan.clean(n).with_loss(30.0)
    st, _ = run_ticks(p, st, plan, seeds_mask(n, [0]), 150)
    diag_status = jnp.diagonal(statuses(st))
    assert bool(jnp.all(jnp.where(st.alive, diag_status == ALIVE, True)))


@pytest.mark.parametrize("shape", [(4, 2), (2, 4)])
def test_mesh2d_equals_single(shape):
    """Viewer×subject 2D sharding must be bit-identical to single-device —
    the 100k layout where full rows no longer fit one chip (PERF.md)."""
    from scalecube_cluster_tpu.parallel import make_mesh2d

    n = 32
    p = small_params(n)
    plan, sm = FaultPlan.clean(n).with_loss(15.0), seeds_mask(n, [0])

    st = kill(init_full_view(n, user_gossip_slots=2, seed=7), 4)
    ref, tr_ref = run_ticks(p, st, plan, sm, 60)

    mesh = make_mesh2d(shape)
    st_sh = shard_state(kill(init_full_view(n, user_gossip_slots=2, seed=7), 4), mesh)
    plan_sh = shard_plan(plan, mesh)
    out, tr_sh = run_ticks(p, st_sh, plan_sh, sm, 60)

    assert bool(jnp.all(jax.device_get(out.view) == jax.device_get(ref.view)))
    assert bool(
        jnp.all(
            jax.device_get(tr_sh["convergence"]) == jax.device_get(tr_ref["convergence"])
        )
    )


def test_user_gossip_message_counts_within_cluster_math_envelope():
    """With per-rumor infected tracking on, total rumor-bearing sends for one
    gossip stay within the ClusterMath ceiling AND below the unsuppressed
    count — the sim twin of GossipProtocolTest.java:176-203 validating
    maxMessagesPerGossipTotal (ClusterMath.java:53-67)."""
    import dataclasses

    from scalecube_cluster_tpu.sim.state import init_full_view as init

    n = 48
    spread = 12
    window = 40  # run past spread so every send for this rumor is counted

    def total_sends(track: bool) -> int:
        p = small_params(
            n, periods_to_spread=spread, periods_to_sweep=30
        )
        p = dataclasses.replace(p, track_user_infected=track)
        st = init(n, user_gossip_slots=1, seed=2, track_infected=track)
        st = inject_gossip(st, 0, 0)
        st, tr = run_ticks(p, st, FaultPlan.clean(n), seeds_mask(n, [0]), window)
        return int(jnp.sum(tr["msgs_user"][:, 0]))

    ceiling = n * 3 * spread  # n × fanout × periodsToSpread (ClusterMath)
    suppressed = total_sends(True)
    unsuppressed = total_sends(False)
    assert suppressed <= ceiling, f"{suppressed} exceeds envelope {ceiling}"
    assert unsuppressed <= ceiling
    # Suppression must actually suppress: strictly fewer sends.
    assert suppressed < unsuppressed


def test_gossip_delay_model_zero_delay_is_bit_invisible():
    """Arming gossip_delay_model with a delay-free plan changes NOTHING —
    bit-for-bit (the immediate-delivery draw is `u < 1.0` with u in [0,1),
    always true; sim/faults.py::link_delay_within_tick). Guards every
    existing trajectory against the round-5 delay-model addition."""
    import dataclasses

    n, ticks = 16, 20
    plan = FaultPlan.clean(n).with_loss(20.0)
    outs = []
    for armed in (False, True):
        p = dataclasses.replace(
            small_params(n, user_gossip_slots=1),
            track_user_infected=True,
            gossip_delay_model=armed,
            tick_ms=50,
        )
        st = init_full_view(
            n, user_gossip_slots=1, seed=5, track_infected=True, delay_model=True
        )
        st = inject_gossip(st, 0, 0)
        st, tr = run_ticks(p, st, plan, seeds_mask(n, [0]), ticks)
        outs.append((st, tr))
    (st_a, tr_a), (st_b, tr_b) = outs
    for field in ("view", "useen", "uage", "uinf", "uflight", "rng"):
        a = jax.device_get(getattr(st_a, field))
        b = jax.device_get(getattr(st_b, field))
        assert (a == b).all(), f"zero-delay divergence in {field}"
    assert not jax.device_get(st_b.uflight).any(), "nothing may be in flight"
    a = jax.device_get(jnp.stack(tr_a["gossip_coverage"]))
    b = jax.device_get(jnp.stack(tr_b["gossip_coverage"]))
    assert (a == b).all()


def test_gossip_delay_model_defers_but_completes():
    """With mean delay ~= the tick span, dissemination slows during the
    transient (copies are genuinely in flight across period boundaries) but
    still completes — delayed, never lost (evaluateDelay semantics,
    NetworkEmulator.java:363-368, period-binned)."""
    import dataclasses

    n, ticks, trials = 24, 24, 6
    cov = {0.0: [], 50.0: []}
    for delay_ms in cov:
        p = dataclasses.replace(
            small_params(n, user_gossip_slots=1, periods_to_spread=12,
                         periods_to_sweep=26),
            track_user_infected=True,
            gossip_delay_model=True,
            tick_ms=50,
            fd_period_ticks=1000,  # gossip-only, like the crossval mesh
            sync_period_ticks=1000,
            suspicion_ticks=1000,
        )
        plan = FaultPlan.clean(n).with_mean_delay(delay_ms)
        for trial in range(trials):
            st = init_full_view(
                n,
                user_gossip_slots=1,
                seed=50 + trial,
                track_infected=True,
                delay_model=True,
            )
            st = inject_gossip(st, 0, 0)
            st, tr = run_ticks(p, st, plan, seeds_mask(n, [0]), ticks)
            cov[delay_ms].append(
                np.asarray(jax.device_get(jnp.stack(tr["gossip_coverage"])))[:, 0]
            )
    fast_c = np.mean(cov[0.0], axis=0)
    slow_c = np.mean(cov[50.0], axis=0)
    assert slow_c[-1] == 1.0, slow_c  # completes
    # Strictly slower somewhere in the transient, never faster on average.
    transient = slice(1, 6)
    assert (slow_c[transient] <= fast_c[transient] + 1e-9).all(), (
        fast_c, slow_c,
    )
    assert slow_c[2] < fast_c[2], (fast_c, slow_c)
