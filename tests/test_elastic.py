"""Elastic membership: capacity-tiered clusters that grow while they run.

Covers the three tentpole layers and their contracts:

- **Parity pin** — ``n_alloc == n_live`` (or ``n_alloc=None``) keeps the
  state pytree and scheduled trajectories bit-identical to fixed-shape
  builds: elasticity is structure-gated, never a tax on non-elastic runs.
- **Promotion** — the checkpoint-based geometry promotion
  (sim/checkpoint.py::promote_sparse_state, driven online by
  ServeBridge.promote) resumes bit-exactly on live rows through a REAL
  ``pack_cold=True`` checkpoint round-trip, certified leaf-by-leaf by
  testlib/invariants.py::certify_promotion (P1-P3) — plus negatives where
  a tampered ``live_mask`` / view corner fails certification.
- **Growth session** — one serve session grows across >= 2 promotions
  under seeded kill/restart traffic: C1-C6 certified per inter-promotion
  segment, the admission conservation ledger exact across the whole
  session, and every join's request -> ack -> admit flight-recorder cause
  chain surviving promotion. The ISSUE-scale 64 -> 512 session runs the
  same harness under ``-m slow``; the tier-1 copy grows 16 -> 128.
- **Rapid twin** — elastic Rapid growth (downward from the top row, so
  every joiner's ring-successor observers are live) with R1-R5 certified
  across a promotion boundary.
"""

from __future__ import annotations

import dataclasses
import io

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from scalecube_cluster_tpu.obs.trace import TK_JOIN_ACK, TK_JOIN_EV, TK_JOIN_REQ
from scalecube_cluster_tpu.obs.tracer import init_trace_ring
from scalecube_cluster_tpu.serve.bridge import ServeBridge
from scalecube_cluster_tpu.serve.ingest import EventBatcher, event_from_obj
from scalecube_cluster_tpu.sim.checkpoint import (
    load_sparse_checkpoint,
    promote_sparse_state,
    save_sparse_checkpoint,
)
from scalecube_cluster_tpu.sim.rapid import (
    RapidParams,
    init_rapid_full_view,
    promote_rapid_state,
    scan_rapid_ticks,
)
from scalecube_cluster_tpu.sim.schedule import FaultPlan, ScheduleBuilder
from scalecube_cluster_tpu.sim.sparse import (
    SparseParams,
    effective_view,
    init_sparse_full_view,
    scan_sparse_ticks,
)
from scalecube_cluster_tpu.testlib.invariants import (
    InvariantViolation,
    certify_promotion,
    certify_rapid_traces,
    certify_traces,
)
from tests.test_sim import small_params

SLOTS = 64


def sparse_params(n_alloc):
    return SparseParams(
        base=small_params(n_alloc), slot_budget=SLOTS, alloc_cap=16
    )


def elastic_state(n_live, n_alloc, seed=7, trace_capacity=0):
    return init_sparse_full_view(
        n_live, slot_budget=SLOTS, seed=seed, n_alloc=n_alloc,
        trace_capacity=trace_capacity,
    )


def grow_schedule(n_alloc, joins, kills=(), restarts=()):
    sb = ScheduleBuilder(n_alloc).add_segment(1, FaultPlan.clean(n_alloc))
    for t, node in joins:
        sb = sb.join(t, node)
    for t, node in kills:
        sb = sb.kill(t, node)
    for t, node in restarts:
        sb = sb.restart(t, node)
    return sb.build(epoch0=0)


def live_conv(state) -> float:
    """live x live knownness fraction — the elastic convergence measure
    (capacity rows are UNKNOWN by contract, so the fixed-shape measure
    would never read 1.0)."""
    lm = np.asarray(jax.device_get(state.live_mask))
    ev = np.asarray(jax.device_get(effective_view(state)))
    known = (ev != -1) & lm[:, None] & lm[None, :]
    return float(known.sum()) / float(lm.sum()) ** 2


# ------------------------------------------------------------- parity pin


def test_fixed_shape_parity_pin():
    """n_alloc == n_live is bit-identical to the fixed-shape init: same
    treedef (same compiled executables), same leaves, and a scheduled
    40-tick trajectory with kill/restart traffic stays bit-exact."""
    n = 32
    params = sparse_params(n)
    s_fixed = init_sparse_full_view(n, slot_budget=SLOTS, seed=5)
    s_alloc = init_sparse_full_view(n, slot_budget=SLOTS, seed=5, n_alloc=n)
    assert jax.tree_util.tree_structure(s_fixed) == jax.tree_util.tree_structure(
        s_alloc
    )
    for a, b in zip(jax.tree_util.tree_leaves(s_fixed),
                    jax.tree_util.tree_leaves(s_alloc)):
        assert bool(jnp.array_equal(a, b))

    sched = grow_schedule(n, joins=[], kills=[(10, 3)], restarts=[(25, 3)])
    f_state, f_tr = scan_sparse_ticks(params, s_fixed, sched, 40)
    a_state, a_tr = scan_sparse_ticks(params, s_alloc, sched, 40)
    for a, b in zip(jax.tree_util.tree_leaves(f_state),
                    jax.tree_util.tree_leaves(a_state)):
        assert bool(jnp.array_equal(a, b))
    for k in f_tr:
        assert bool(jnp.array_equal(f_tr[k], a_tr[k])), k


def test_legacy_join_alias_is_flagged():
    """The SWIM join->restart alias survives under legacy_join=True (the
    fixed-shape default) and routes to admission when an allocator is
    wired — the trace-format switch documented in serve/ingest.py."""
    from scalecube_cluster_tpu.serve.events import EV_JOIN, EV_RESTART

    legacy = EventBatcher(8, 4, 4, 4)
    ev = event_from_obj({"kind": "join", "node": 3})
    legacy.push(ev, stamp=False)
    assert ev.kind == EV_RESTART  # byte-compatible alias preserved

    rows = iter(range(4, 8))
    elastic = EventBatcher(
        8, 4, 4, 4, legacy_join=False, admit=lambda e: next(rows, None)
    )
    ev2 = event_from_obj({"kind": "join"})  # node omitted: elastic wire form
    elastic.push(ev2, stamp=False)
    assert ev2.kind == EV_JOIN and ev2.node == 4
    for _ in range(4):
        elastic.push(event_from_obj({"kind": "join"}), stamp=False)
    led = elastic.assert_join_conservation()
    assert led == {
        "requested": 5, "admitted": 4, "placed": 0,
        "pending": 4, "deferred": 1, "shed": 0,
    }
    assert elastic.replay_deferred_joins() == 0  # still no capacity
    assert len(elastic.deferred_joins) == 1


# ------------------------------------------------------------- promotion


def _grown_state(trace_capacity=0):
    """A 24-live-in-32 state with join/kill/restart history — suspicion
    and incarnation planes populated so the round-trip exercises every
    leaf, including the packed int16 cold lanes."""
    n_live, n_alloc = 24, 32
    params = sparse_params(n_alloc)
    state = elastic_state(n_live, n_alloc, trace_capacity=trace_capacity)
    sched = grow_schedule(
        n_alloc,
        joins=[(20, 24), (50, 25)],
        kills=[(10, 3)],
        restarts=[(60, 3)],
    )
    state, _ = scan_sparse_ticks(params, state, sched, 160)
    return params, state


def test_promotion_roundtrip_bit_exact():
    """save(pack_cold=True) -> load -> promote certifies P1-P3, and the
    promoted session stays protocol-clean: scheduled joins land on the new
    capacity rows and C1-C6 certify across the boundary."""
    params, state = _grown_state(trace_capacity=2048)
    buf = io.BytesIO()
    save_sparse_checkpoint(
        buf, state.replace(trace=None), params, pack_cold=True
    )
    buf.seek(0)
    state_l, params_l = load_sparse_checkpoint(buf)
    for f in dataclasses.fields(type(state)):
        a, b = getattr(state, f.name), getattr(state_l, f.name)
        if f.name == "trace":
            continue
        if a is None:
            assert b is None, f.name
        else:
            assert bool(jnp.array_equal(a, b)), f.name
    state_l = state_l.replace(trace=state.trace)

    params2, state2 = promote_sparse_state(params_l, state_l, 64)
    summary = certify_promotion(params, state, params2, state2)
    assert summary["n_old"] == 32 and summary["n_new"] == 64
    assert summary["p3_checked"]

    t0 = int(jax.device_get(state2.tick))
    sched = grow_schedule(
        64, joins=[(t0 + 20, 32), (t0 + 50, 33)], kills=[(t0 + 80, 5)],
        restarts=[(t0 + 140, 5)],
    )
    state2, tr = scan_sparse_ticks(params2, state2, sched, 600)
    assert int(jnp.sum(tr["joins_fired"])) == 2
    assert int(jnp.sum(state2.live_mask)) == 28
    certify_traces(params2.base, tr)
    assert live_conv(state2) == 1.0


def test_tampered_promotion_fails_certification():
    params, state = _grown_state()
    params2, state2 = promote_sparse_state(params, state, 64)

    ghost = state2.replace(live_mask=state2.live_mask.at[50].set(True))
    with pytest.raises(InvariantViolation, match="P2-capacity-rows"):
        certify_promotion(params, state, params2, ghost)

    rewritten = state2.replace(view_T=state2.view_T.at[3, 5].add(1))
    with pytest.raises(InvariantViolation, match="P1-live-rows"):
        certify_promotion(params, state, params2, rewritten)

    with pytest.raises(ValueError, match="must grow"):
        promote_sparse_state(params2, state2, 64)


# -------------------------------------------------- growth serve session


def _segment_traces(launches):
    """Stack per-launch trace dicts into one [ticks] segment trace."""
    keys = launches[0].keys()
    return {k: np.concatenate([np.asarray(tr[k]) for tr in launches])
            for k in keys}


def _walk_join_chains(state, n_expected):
    """Every TK_JOIN_EV must close a request -> ack -> admit cause chain."""
    ring = state.trace
    kinds = np.asarray(jax.device_get(ring.ev_kind))
    causes = np.asarray(jax.device_get(ring.ev_cause))
    cur = int(jax.device_get(ring.cursor))
    ev_pos = np.flatnonzero(kinds[:cur] == TK_JOIN_EV)
    assert len(ev_pos) == n_expected, (len(ev_pos), n_expected)
    for p in ev_pos:
        ack = causes[p]
        assert ack >= 0 and kinds[ack] == TK_JOIN_ACK, int(p)
        req = causes[ack]
        assert req >= 0 and kinds[req] == TK_JOIN_REQ, int(ack)


def _run_growth_session(n_live0, n_alloc0, tiers, rng_seed=11, burst=12):
    """Grow one serve session to full occupancy of the top tier through
    ``tiers`` promotions, under seeded kill/restart traffic racing the
    joins. ``burst`` joins arrive per launch — keep it under the base
    tier's free capacity so every tier actually serves launches.
    Returns (bridge, per-segment launch trace lists)."""
    params = sparse_params(n_alloc0)
    state = elastic_state(
        n_live0, n_alloc0, trace_capacity=64 * n_alloc0 * (2 ** tiers)
    )
    bridge = ServeBridge(
        params, state, batch_ticks=8, capacity=16, auto_promote=True,
    )
    rng = np.random.default_rng(rng_seed)
    n_top = n_alloc0 * (2 ** tiers)
    n_joins = n_top - n_live0

    segments, current = [], []
    joins_sent = 0
    # Trickle joins in so admission, capacity exhaustion, promotion and
    # replay all happen mid-session, racing the kill/restart traffic.
    while bridge.promotions < tiers or len(bridge.batcher.deferred_joins) or joins_sent < n_joins:
        b = min(burst, n_joins - joins_sent)
        for _ in range(b):
            bridge.push(event_from_obj({"kind": "join"}))
        joins_sent += b
        victim = int(rng.integers(0, n_live0))
        bridge.push(event_from_obj({"kind": "kill", "node": victim}))
        bridge.push(event_from_obj({"kind": "restart", "node": victim}))
        p_before = bridge.promotions
        tr = bridge.step_batch()
        if bridge.promotions > p_before:
            # step_batch promoted BEFORE this launch ran, so its trace
            # belongs to the new geometry's segment.
            segments.append(current)
            current = []
        current.append(tr)
        assert bridge.batcher.assert_join_conservation()
    # settle: let the last admissions fire and the cluster converge.
    for _ in range(6):
        current.append(bridge.step_batch())
    segments.append(current)
    return bridge, segments


def _certify_growth(bridge, segments, n_live0, n_alloc0, tiers):
    assert bridge.promotions == tiers
    n_top = n_alloc0 * (2 ** tiers)
    assert bridge.params.base.n == n_top
    c = bridge.counters()
    assert c["n_live"] == n_top
    assert c["promotions"] == tiers
    assert c["joins_deferred"] == 0
    led = bridge.batcher.assert_join_conservation()
    assert led["requested"] == n_top - n_live0
    assert led["placed"] == n_top - n_live0  # zero dropped, all served
    assert led["deferred"] == 0 and led["shed"] == 0
    # C1-C6 per inter-promotion segment, each certified on the CUMULATIVE
    # trace up to its boundary: live rows carry verbatim across a promotion
    # (P1), so C6's miss -> suspicion causality legitimately crosses it.
    # Every C1-C6 check is per-tick or monotone, so each prefix run covers
    # its newest segment at full strength.
    assert len(segments) == tiers + 1
    tier_n = [n_alloc0 * (2 ** i) for i in range(tiers + 1)]
    flat = []
    for n_seg, launches in zip(tier_n, segments):
        flat.extend(launches)
        certify_traces(small_params(n_seg), _segment_traces(flat))
    _walk_join_chains(bridge.state, n_top - n_live0)


def test_grow_serve_session_two_promotions():
    """Tier-1 scale: 16 live in 32 alloc grows to a full 128 across two
    promotions under kill/restart traffic — segments certified, ledger
    exact, cause chains intact across both boundaries."""
    bridge, segments = _run_growth_session(16, 32, tiers=2)
    _certify_growth(bridge, segments, 16, 32, tiers=2)


@pytest.mark.slow
def test_grow_64_to_512_certified():
    """ISSUE scale: one session grows n_live 64 -> 512 across two
    promotions (128 -> 256 -> 512), zero dropped events, per-segment
    certification, ledger exact, chains surviving both promotions."""
    bridge, segments = _run_growth_session(64, 128, tiers=2, burst=24)
    _certify_growth(bridge, segments, 64, 128, tiers=2)
    assert live_conv(bridge.state) > 0.25  # converging; full heal is long


# ---------------------------------------------------------- rapid twin


def test_rapid_elastic_growth_certified():
    """Elastic Rapid: capacity rows join DOWNWARD from the top row (their
    ring-successor observers wrap onto live rows — a joiner above a dead
    arc could never accumulate H join-alarms), paced so each admission
    lands before the next join fires. R1-R5 certify across a kill, four
    admissions, and a geometry promotion."""
    params = RapidParams(n=32, k=8)
    state = init_rapid_full_view(params, seed=2, n_live=24)
    sb = ScheduleBuilder(32).add_segment(1, FaultPlan.clean(32)).kill(5, 3)
    for i, t in enumerate([30, 60, 90, 120]):
        sb = sb.join(t, 31 - i)
    state, tr = scan_rapid_ticks(params, state, sb.build(epoch0=0), 160)
    assert int(jnp.sum(tr["joins_fired"])) == 4
    assert int(jnp.sum(state.live_mask)) == 28
    certify_rapid_traces(params, tr)

    params2, state2 = promote_rapid_state(params, state, 64)
    assert params2.n == 64
    mm_old = np.asarray(jax.device_get(state.member_mask))
    mm_new = np.asarray(jax.device_get(state2.member_mask))
    assert np.array_equal(mm_old, mm_new[:32, :32])
    assert int(jax.device_get(state2.tick)) == int(jax.device_get(state.tick))

    t0 = int(jax.device_get(state2.tick))
    sb2 = ScheduleBuilder(64).add_segment(t0 + 1, FaultPlan.clean(64))
    for i, t in enumerate([t0 + 15, t0 + 45]):
        sb2 = sb2.join(t, 63 - i)
    state2, tr2 = scan_rapid_ticks(params2, state2, sb2.build(epoch0=0), 240)
    assert int(jnp.sum(tr2["joins_fired"])) == 2
    assert int(jnp.sum(state2.live_mask)) == 30
    certify_rapid_traces(params2, tr2)
