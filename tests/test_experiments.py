"""The BASELINE.json scenario grid runs end-to-end at CI scale."""

from scalecube_cluster_tpu.experiments import run_all


def test_small_grid_passes():
    results = {r["scenario"]: r for r in run_all("small")}

    assert results["join"]["converged"]
    assert results["lossy_suspicion"]["false_deaths"] == 0
    assert results["lossy_suspicion"]["final_convergence"] > 0.95
    assert results["partition_recovery"]["partition_detected"]
    assert results["partition_recovery"]["healed_convergence"] == 1.0
    assert results["churn"]["final_convergence"] > 0.9
