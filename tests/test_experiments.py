"""The BASELINE.json scenario grid runs end-to-end at CI scale."""

import pytest

from scalecube_cluster_tpu.experiments import run_all


@pytest.mark.deep
def test_small_grid_passes():
    results = {r["scenario"]: r for r in run_all("small")}

    assert results["join"]["converged"]
    assert results["lossy_suspicion"]["false_deaths"] == 0
    assert results["lossy_suspicion"]["final_convergence"] > 0.95
    assert results["partition_recovery"]["partition_detected"]
    assert results["partition_recovery"]["healed_convergence"] == 1.0
    assert results["churn"]["final_convergence"] > 0.9
    churn = results["sparse_churn"]
    assert churn["churned_down"] > 0
    # At CI scale (n=256, budget 2048) churn activity must fit the slot
    # table with real headroom and never drop an activation request.
    assert churn["active_slots"] < churn["slot_budget"] // 2, churn
    assert churn["slot_overflow_total"] == 0.0, churn
