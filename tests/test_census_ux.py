"""Census drift UX, shared across the gated tiers 2-4.

Each jaxpr-reading tier pins its traced surface as a committed golden
(R10: artifacts/jax_census.json, S4: collective_census.json, G4:
shardflow_census.json) with the same contract: a missing golden is an
"unpinned" finding not a crash, drift produces a reviewable diff, and the
``--*census-update`` re-pin round-trips to a clean next run. One
parametrized suite exercises the contract for all three census modules,
so a UX regression in one tier can't hide behind the others' copies.
"""

from __future__ import annotations

import pytest

from tools.lint.semantic import jax_unavailable_reason

if jax_unavailable_reason() is not None:  # pragma: no cover - env-dependent
    pytest.skip(
        f"census modules need jax: {jax_unavailable_reason()}",
        allow_module_level=True,
    )

import jax

from tools.lint.semantic import census as semantic_census
from tools.lint.shardflow import census as shardflow_census
from tools.lint.spmdcheck import census as spmd_census


def _semantic_row(variant: str) -> dict:
    return {
        "jaxpr_digest": variant,
        "n_eqns": 3,
        "primitives": {"add": 2, "mul": 1},
        "carry_treedef": "",
        "donated_leaves": 0,
        "alias_outputs": [],
        "path": "x.py",
    }


def _spmd_row(variant: str) -> dict:
    return {
        "digest": variant,
        "collectives": [],
        "path": "x.py",
        "exchange_rounds_per_tick": 3,
        "traced_exchange_bytes_per_tick": 0,
        "traced_reduce_bytes_per_tick": 0,
    }


def _shardflow_row(variant: str) -> dict:
    return {
        "digest": variant,
        "path": "x.py",
        "mesh": {"a": 2},
        "n": 8,
        "in_shardings": ["(a,_)"],
        "out_shardings": ["(a,_)" if variant == "old" else "(?,_)"],
        "g1_origins": [],
        "g2_crossing_bytes": 0,
        "g2_crossing_sites": 0,
        "reduce_hazards": 0,
        "hbm_budget_bytes": 1 << 30,
    }


TIERS = [
    pytest.param(semantic_census, "R10", _semantic_row, id="semantic-R10"),
    pytest.param(spmd_census, "S4", _spmd_row, id="spmd-S4"),
    pytest.param(shardflow_census, "G4", _shardflow_row, id="shardflow-G4"),
]


def _census(mod, row_fn, variant: str, name: str = "e") -> dict:
    return mod.build_census({name: row_fn(variant)}, jax.__version__)


@pytest.mark.parametrize("mod,rule,row_fn", TIERS)
def test_missing_golden_flags_unpinned(mod, rule, row_fn, tmp_path):
    new = _census(mod, row_fn, "new")
    findings, _ = mod.compare(
        mod.load_census(tmp_path / "absent.json"), new, tmp_path / "absent.json"
    )
    assert [f.rule for f in findings] == [rule]
    assert "unpinned" in findings[0].message


@pytest.mark.parametrize("mod,rule,row_fn", TIERS)
def test_drift_detected_with_reviewable_diff(mod, rule, row_fn, tmp_path):
    old = _census(mod, row_fn, "old")
    new = _census(mod, row_fn, "new")
    findings, diff = mod.compare(old, new, tmp_path / "c.json")
    assert any(f.rule == rule and "drifted" in f.message for f in findings)
    assert any("~ e" in line for line in diff), diff
    # Every drift finding tells the reviewer how to deliberately re-pin.
    assert all("update" in f.hint for f in findings if f.rule == rule)


@pytest.mark.parametrize("mod,rule,row_fn", TIERS)
def test_new_and_vanished_entries_flag(mod, rule, row_fn, tmp_path):
    old = _census(mod, row_fn, "old", name="kept")
    new = mod.build_census(
        {"kept": row_fn("old"), "added": row_fn("old")}, jax.__version__
    )
    findings, diff = mod.compare(old, new, tmp_path / "c.json")
    assert any("new since" in f.message for f in findings)
    assert any("+ added" in line for line in diff)
    findings, diff = mod.compare(new, old, tmp_path / "c.json")
    assert any("vanished" in f.message for f in findings)
    assert any("- added" in line for line in diff)


@pytest.mark.parametrize("mod,rule,row_fn", TIERS)
def test_repin_roundtrip_is_clean(mod, rule, row_fn, tmp_path):
    """write_census -> load_census -> compare is drift-free: what
    ``--*census-update`` pins is exactly what the next run rebuilds."""
    census = _census(mod, row_fn, "new")
    golden = tmp_path / "golden.json"
    mod.write_census(census, golden)
    findings, diff = mod.compare(mod.load_census(golden), census, golden)
    assert findings == []
    assert diff == []
