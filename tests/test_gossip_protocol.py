"""Gossip dissemination experiments.

Ports GossipProtocolTest.java:44-297: parameterized {N, loss%} grids
asserting complete dissemination, **no double delivery**, dissemination
within the sweep deadline, and spread() completion at sweep — measured
against the ClusterMath predictions.
"""

from __future__ import annotations

import asyncio
import random

import pytest

from scalecube_cluster_tpu import cluster_math
from scalecube_cluster_tpu.cluster.gossip import GossipProtocol
from scalecube_cluster_tpu.cluster_api.config import GossipConfig
from scalecube_cluster_tpu.cluster_api.member import Member
from scalecube_cluster_tpu.cluster_api.membership_event import MembershipEvent
from scalecube_cluster_tpu.testlib import NetworkEmulatorTransport, await_until
from scalecube_cluster_tpu.transport.message import Message
from scalecube_cluster_tpu.transport.tcp import TcpTransport

GOSSIP_CONFIG = GossipConfig(gossip_interval=50, gossip_fanout=3, gossip_repeat_mult=3)


class GossipNode:
    def __init__(self, transport: NetworkEmulatorTransport, member: Member):
        self.transport = transport
        self.member = member
        self.protocol = GossipProtocol(
            transport, member, GOSSIP_CONFIG, rng=random.Random(member.id)
        )
        self.received: list[Message] = []
        self._watch: asyncio.Task | None = None

    def start(self, peers: list["GossipNode"]) -> None:
        for peer in peers:
            if peer is not self:
                self.protocol.on_membership_event(MembershipEvent.added(peer.member))
        self.protocol.start()
        self._watch = asyncio.create_task(self._watch_messages())

    async def _watch_messages(self) -> None:
        async for msg in self.protocol.listen():
            self.received.append(msg)

    async def stop(self) -> None:
        if self._watch:
            self._watch.cancel()
        self.protocol.stop()
        await self.transport.stop()


async def make_mesh(
    n: int, loss_percent: float = 0.0, mean_delay_ms: float = 0.0
) -> list[GossipNode]:
    nodes = []
    for i in range(n):
        transport = NetworkEmulatorTransport(await TcpTransport.bind(), seed=i)
        if loss_percent or mean_delay_ms:
            transport.network_emulator.set_default_outbound_settings(
                loss_percent, mean_delay_ms
            )
        nodes.append(GossipNode(transport, Member.create(transport.address)))
    for node in nodes:
        node.start(nodes)
    return nodes


async def stop_mesh(nodes: list[GossipNode]) -> None:
    await asyncio.gather(*(n.stop() for n in nodes), return_exceptions=True)


@pytest.mark.asyncio
@pytest.mark.parametrize(
    "n,loss,delay",
    [
        # The reference experiment grid corners (GossipProtocolTest.java:48-64):
        # N up to 50, loss up to 50%, exponential mean delay up to 100 ms.
        (2, 0.0, 0.0),
        (6, 0.0, 0.0),
        (10, 20.0, 0.0),
        (10, 50.0, 0.0),
        (10, 10.0, 100.0),
        (50, 0.0, 2.0),
        (50, 25.0, 0.0),
        # The harshest reference corners (VERDICT round-2 weak#6): the full
        # cross product reaches N=50 × loss=50% and N=50 × delay=100ms.
        (50, 50.0, 0.0),
        (50, 0.0, 100.0),
    ],
)
async def test_complete_dissemination_exactly_once(n: int, loss: float, delay: float):
    """Every node receives the rumor exactly once, within the sweep deadline
    (GossipProtocolTest.java:154-173)."""
    nodes = await make_mesh(n, loss, delay)
    try:
        origin = nodes[0]
        origin.protocol.spread(
            Message.create(qualifier="rumor", data="payload")
        )
        deadline_ms = cluster_math.gossip_timeout_to_sweep(
            GOSSIP_CONFIG.gossip_repeat_mult, n, GOSSIP_CONFIG.gossip_interval
        )
        await await_until(
            lambda: all(len(peer.received) >= 1 for peer in nodes[1:]),
            timeout=deadline_ms / 1000.0 + 2.0 + 4 * delay / 1000.0,
        )
        # settle, then assert exactly-once (dedup by gossip id)
        await asyncio.sleep(0.5)
        for peer in nodes[1:]:
            assert len(peer.received) == 1, f"double delivery at {peer.member}"
            assert peer.received[0].data == "payload"
    finally:
        await stop_mesh(nodes)


@pytest.mark.asyncio
async def test_spread_future_resolves_at_sweep():
    """spread() completes with the gossip id once the rumor is swept
    (GossipProtocolImpl.java:299-302)."""
    nodes = await make_mesh(4)
    try:
        fut = nodes[0].protocol.spread(Message.create(qualifier="r", data=1))
        gossip_id = await asyncio.wait_for(fut, timeout=10)
        assert gossip_id.startswith(nodes[0].member.id)
        assert not nodes[0].protocol._gossips  # swept
    finally:
        await stop_mesh(nodes)


@pytest.mark.asyncio
async def test_message_bound_respects_cluster_math():
    """Per-node sends for one gossip stay within the ClusterMath upper bound
    (GossipProtocolTest.java:176-203 logs measured vs theory)."""
    n = 6
    nodes = await make_mesh(n)
    try:
        origin = nodes[0]
        sent_before = origin.transport.network_emulator.total_message_sent_count
        fut = origin.protocol.spread(Message.create(qualifier="r", data=1))
        await asyncio.wait_for(fut, timeout=15)
        sent = origin.transport.network_emulator.total_message_sent_count - sent_before
        bound = cluster_math.max_messages_per_gossip_per_node(
            GOSSIP_CONFIG.gossip_fanout, GOSSIP_CONFIG.gossip_repeat_mult, n
        )
        assert sent <= bound, f"{sent} sends exceed ClusterMath bound {bound}"
    finally:
        await stop_mesh(nodes)
