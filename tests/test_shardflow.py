"""tpulint tier-4 tests: GSPMD sharding propagation (G1-G3) and the
sharding census (G4).

Mirrors the tier-3 contract in tests/test_tpulint_spmd.py:
  1. every detector is demonstrated by a fixture that trips exactly it —
     a deliberately-divergent dual-sharded point-gather feeding a second
     sharded gather (G1), a cross-shard gather blowing a tiny HBM budget
     (G2), a reduction over a sharding-merging reshape (G3),
  2. the sanctioned idioms stay silent — the shard-invariant-cursor twin
     and the single-axis-layout twin of the G1 fixture (both candidate
     fix shapes for the 2D FD divergence),
  3. the shipped GSPMD entries pin clean against the committed sharding
     census (the shared session run from conftest), with the ONE known
     G1 — the 2D FD probe-selection divergence the runtime xfail
     tests/test_spmd.py::test_2d_mesh_divergence_bisected_to_fd_probe_selection
     bisected — carried by exactly one justified pragma in sim/sparse.py.

Nothing here executes on devices: propagation is abstract interpretation
over traced jaxprs, so the fixtures only pay tracing.
"""

from __future__ import annotations

from pathlib import Path

import pytest

from tools.lint.semantic import jax_unavailable_reason

if jax_unavailable_reason() is not None:  # pragma: no cover - env-dependent
    pytest.skip(
        f"shardflow tier needs jax: {jax_unavailable_reason()}",
        allow_module_level=True,
    )

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from tools.lint.shardflow import rules as rules_mod
from tools.lint.shardflow.domain import (
    REP,
    UNKNOWN,
    join_dim,
    join_sv,
    replicated,
    sv_from_pspec,
)
from tools.lint.shardflow.entries import TracedShardflowEntry
from tools.lint.shardflow.propagate import ShardflowInterp

REPO = Path(__file__).resolve().parent.parent

N = 16


# ---------------------------------------------------------------- domain


def test_join_dim_lattice():
    a = frozenset({"a"})
    b = frozenset({"b"})
    assert join_dim(REP, a) == a
    assert join_dim(a, a) == a
    assert join_dim(a, b) is UNKNOWN
    assert join_dim(UNKNOWN, a) is UNKNOWN
    assert join_dim(REP, REP) == REP


def test_sv_from_pspec_and_render():
    sv = sv_from_pspec(P("a", None), 3)
    assert sv.dims == (frozenset({"a"}), REP, REP)
    assert sv.render() == "(a,_,_)"
    assert sv_from_pspec(None, 2).dims == (REP, REP)
    tup = sv_from_pspec(P(("a", "b")), 1)
    assert tup.dims == (frozenset({"a", "b"}),)
    assert tup.render() == "(a+b)"


def test_join_sv_taint_union():
    x = sv_from_pspec(P("a"), 1)
    y = replicated(1)
    tainted = type(x)(
        dims=y.dims, deps=frozenset({"b"}), origin=("f.py", 3)
    )
    j = join_sv(x, tainted)
    assert j.deps == frozenset({"b"})
    assert j.origin == ("f.py", 3)


# ------------------------------------------------------------- fixtures


def _run_fixture(fn, args, specs, mesh_axes=("a", "b"), hbm_budget=1 << 30):
    """Trace a fixture jit, seed SVs from its PartitionSpecs, propagate,
    and run the rule pack — the path run_shardflow takes per entry."""
    closed = jax.jit(fn).trace(*args).jaxpr
    invars = closed.jaxpr.invars
    assert len(specs) == len(invars)
    in_svs = [
        sv_from_pspec(s, len(v.aval.shape)) for s, v in zip(specs, invars)
    ]
    interp = ShardflowInterp(
        frozenset(mesh_axes), root=str(REPO), fallback_site=("fixture.py", 1)
    )
    out_svs = interp.run(closed.jaxpr, in_svs)
    entry = TracedShardflowEntry(
        name="fixture",
        path="fixture.py",
        line=1,
        closed=closed,
        mesh=None,
        in_svs=in_svs,
        in_specs=list(specs),
        n=N,
        hbm_budget=hbm_budget,
    )
    findings = rules_mod.check_entry(entry, interp.events, REPO)
    return findings, interp, out_svs


def _divergent(x, tbl):
    """The 2D FD probe-selection shape, minimised: a data-dependent
    cursor resolved through a DUAL-sharded point-gather, then used to
    index another sharded table."""
    rows = jnp.arange(x.shape[0], dtype=jnp.int32)
    cur = jnp.argmax(x, axis=1).astype(jnp.int32)
    v = x[cur, rows]  # point-gather across BOTH mesh axes -> taint
    tgt = v.astype(jnp.int32) % x.shape[0]
    return tbl[tgt]  # tainted indices cross the sharded table -> fires


def test_g1_divergent_2d_gather_fires():
    x = jnp.zeros((N, N), jnp.float32)
    tbl = jnp.arange(N, dtype=jnp.int32)
    findings, interp, _ = _run_fixture(
        _divergent, (x, tbl), [P("a", "b"), P("a")]
    )
    g1 = [f for f in findings if f.rule == "G1"]
    assert len(g1) == 1, [f.render() for f in findings]
    injected = [e for e in interp.events if e.injected]
    assert len(injected) == 1
    # The finding dedupes to the taint ORIGIN (the dual-sharded gather),
    # not the downstream table read that exhibited it.
    assert (g1[0].path, g1[0].line) == (injected[0].path, injected[0].line)
    assert "test_2d_mesh_divergence_bisected_to_fd_probe_selection" in (
        g1[0].message
    )


def test_g1_shard_invariant_cursor_twin_silent():
    """Candidate fix shape 1: the table is indexed by a shard-invariant
    cursor; the dual-sharded read still happens but its value never
    steers a cross-shard access, so nothing fires."""

    def twin(x, tbl):
        rows = jnp.arange(x.shape[0], dtype=jnp.int32)
        cur = jnp.argmax(x, axis=1).astype(jnp.int32)
        v = x[cur, rows]  # still injects taint...
        return tbl[rows] + v.astype(jnp.int32)  # ...but nothing uses it

    x = jnp.zeros((N, N), jnp.float32)
    tbl = jnp.arange(N, dtype=jnp.int32)
    findings, interp, _ = _run_fixture(twin, (x, tbl), [P("a", "b"), P("a")])
    assert [f for f in findings if f.rule == "G1"] == [], [
        f.render() for f in findings
    ]
    assert [e for e in interp.events if e.fired] == []


def test_g1_single_axis_layout_twin_silent():
    """Candidate fix shape 2: the record table carries ONE sharded axis
    (the replicated-subject layout) — the point-gather no longer spans
    two mesh axes, so no taint is ever born."""
    x = jnp.zeros((N, N), jnp.float32)
    tbl = jnp.arange(N, dtype=jnp.int32)
    findings, interp, _ = _run_fixture(
        _divergent, (x, tbl), [P("a", None), P("a")]
    )
    assert [f for f in findings if f.rule == "G1"] == [], [
        f.render() for f in findings
    ]
    assert [e for e in interp.events if e.injected] == []


def test_g2_budget_blowout_flags():
    def crossing(x, idx):
        return x[idx]  # row-gather across the sharded dim

    x = jnp.zeros((N, 64), jnp.float32)
    idx = jnp.arange(N, dtype=jnp.int32)
    findings, _, _ = _run_fixture(
        crossing, (x, idx), [P("a", None), P()], hbm_budget=16
    )
    g2 = [f for f in findings if f.rule == "G2"]
    assert len(g2) == 1
    assert "exceeds the entry HBM budget" in g2[0].message
    # Same program under a sane budget: silent.
    findings, _, _ = _run_fixture(crossing, (x, idx), [P("a", None), P()])
    assert [f for f in findings if f.rule == "G2"] == []


def test_g3_reduction_over_degraded_sharding_flags():
    def degraded(x):
        flat = x.reshape(-1)  # merging reshape: sharding -> Unknown
        return jnp.sum(flat)

    x = jnp.zeros((N, N), jnp.float32)
    findings, _, _ = _run_fixture(degraded, (x,), [P("a", None)])
    g3 = [f for f in findings if f.rule == "G3"]
    assert len(g3) == 1
    assert "Unknown" in g3[0].message


def test_g3_clean_sharded_reduction_silent():
    def clean(x):
        return jnp.sum(x, axis=0)  # reduce straight over the sharded dim

    x = jnp.zeros((N, N), jnp.float32)
    findings, _, _ = _run_fixture(clean, (x,), [P("a", None)])
    assert [f for f in findings if f.rule == "G3"] == []


def test_scan_carry_propagates_sharding():
    def scanned(x):
        def body(c, _):
            return c * 2.0, jnp.sum(c)

        out, ys = jax.lax.scan(body, x, None, length=3)
        return out, ys

    x = jnp.zeros((N,), jnp.float32)
    _, _, out_svs = _run_fixture(scanned, (x,), [P("a")])
    assert out_svs[0].dims == (frozenset({"a"}),)  # carry keeps the axis
    assert out_svs[1].dims[0] == REP  # stacked ys leading dim is the loop


# ------------------------------------- the shipped surface (shared run)


def test_shipped_gspmd_entries_clean(shardflow_result):
    """The library passes its own tier-4 gate: the one known G1 is pragma
    -justified, G2/G3 are silent, and the rebuilt sharding census matches
    the committed artifacts/shardflow_census.json."""
    assert shardflow_result.skipped is None
    assert shardflow_result.entries_traced == 6
    assert shardflow_result.eqns_interpreted > 1000
    assert shardflow_result.gated == [], "\n".join(
        f.render() for f in shardflow_result.gated
    )
    assert shardflow_result.diff == [], "sharding census drifted:\n" + "\n".join(
        shardflow_result.diff
    )
    assert shardflow_result.census is not None


def test_sharding_census_golden_matches_run(shardflow_result):
    from tools.lint.shardflow import census as census_mod

    golden = census_mod.load_census(
        REPO / "artifacts" / "shardflow_census.json"
    )
    assert golden is not None, "artifacts/shardflow_census.json not committed"
    assert golden["digest"] == shardflow_result.census["digest"]


def test_2d_entry_fires_g1_at_bisected_site(shardflow_result):
    """The 2D viewers×subjects entry carries EXACTLY ONE G1 origin — the
    my_record_of view_T read in sim/sparse.py, the site the runtime xfail
    bisected to FD probe selection — and every other entry carries none."""
    entries = shardflow_result.census["entries"]
    two_d = entries["sim.sparse.run_sparse_ticks[gspmd2d,2x2]"]
    assert len(two_d["g1_origins"]) == 1
    assert two_d["g1_origins"][0]["path"] == "scalecube_cluster_tpu/sim/sparse.py"
    for name, row in entries.items():
        if name == "sim.sparse.run_sparse_ticks[gspmd2d,2x2]":
            continue
        assert row["g1_origins"] == [], name


def test_exactly_one_justified_g1_pragma():
    """Acceptance pin: ONE G1 pragma in the library, at the bisected FD
    probe-selection site, naming the runtime xfail."""
    lib = REPO / "scalecube_cluster_tpu"
    hits = []
    for path in sorted(lib.rglob("*.py")):
        for i, line in enumerate(path.read_text().splitlines(), 1):
            if "tpulint" in line and "disable=G1" in line:
                hits.append((path.relative_to(REPO).as_posix(), i, line))
    assert len(hits) == 1, hits
    path, _, line = hits[0]
    assert path == "scalecube_cluster_tpu/sim/sparse.py"
    assert "test_2d_mesh_divergence_bisected_to_fd_probe_selection" in line


def test_g1_pragma_covers_census_origin(shardflow_result):
    """The committed census's G1 fingerprint is exactly the finding the
    pragma suppresses: recompute it from the origin's source line."""
    import hashlib

    row = shardflow_result.census["entries"][
        "sim.sparse.run_sparse_ticks[gspmd2d,2x2]"
    ]
    origin = row["g1_origins"][0]
    src = (REPO / origin["path"]).read_text().splitlines()
    matches = [
        ln
        for ln in src
        if "view_T[subject, viewer]" in ln and "tpulint" not in ln
    ]
    assert len(matches) == 1
    basis = f"{origin['path']}:G1:{matches[0].strip()}"
    assert (
        hashlib.sha1(basis.encode()).hexdigest()[:12]
        == origin["fingerprint"]
    )
