"""Membership-protocol distributed scenarios.

Ports the core of MembershipProtocolTest.java:40-1086: 3-node joins,
outbound-block partitions with suspicion-timeout removal and recovery,
all-nodes-outbound blackout, one-way (inbound) partitions with removal and
rejoin, pairwise-link partitions that must evict nobody, restart at the
same port and on fresh ports, seed-chain joins, sync-group isolation, and
self-refutation (incarnation bump) under false suspicion.
"""

from __future__ import annotations

import asyncio

import pytest

from scalecube_cluster_tpu.cluster_api.member import MemberStatus
from scalecube_cluster_tpu.testlib import (
    await_until,
    fast_test_config,
    shutdown_all,
    start_node,
    suspicion_settle_time,
)


def views_converged(clusters, n) -> bool:
    return all(len(c.members()) == n for c in clusters)


@pytest.mark.asyncio
async def test_three_node_join():
    """Seed + two joiners all see a 3-member view
    (MembershipProtocolTest.java:69-91)."""
    seed = await start_node()
    a = await start_node(seeds=(seed.address,))
    b = await start_node(seeds=(seed.address,))
    try:
        await await_until(lambda: views_converged([seed, a, b], 3), timeout=10)
        ids = {m.id for m in seed.members()}
        assert ids == {seed.member().id, a.member().id, b.member().id}
    finally:
        await shutdown_all(seed, a, b)


@pytest.mark.asyncio
async def test_seed_chain_join():
    """C only knows B, B only knows A: the views still converge to 3
    (MembershipProtocolTest.java:523-552)."""
    a = await start_node()
    b = await start_node(seeds=(a.address,))
    c = await start_node(seeds=(b.address,))
    try:
        await await_until(lambda: views_converged([a, b, c], 3), timeout=10)
    finally:
        await shutdown_all(a, b, c)


@pytest.mark.asyncio
async def test_partitioned_member_removed_then_rejoins():
    """Fully partition one node: the rest suspect it and remove it after the
    suspicion timeout; healing the partition re-admits it
    (MembershipProtocolTest.java:94-263, 321-371)."""
    seed = await start_node()
    a = await start_node(seeds=(seed.address,))
    b = await start_node(seeds=(seed.address,))
    clusters = [seed, a, b]
    try:
        await await_until(lambda: views_converged(clusters, 3), timeout=10)
        # partition b both directions
        b.network_emulator.block_all_outbound()
        b.network_emulator.block_all_inbound()
        await await_until(
            lambda: len(seed.members()) == 2 and len(a.members()) == 2,
            timeout=suspicion_settle_time(3) + 5,
        )
        assert seed.member_by_id(b.member().id) is None
        # heal: periodic SYNC from b re-introduces it
        b.network_emulator.unblock_all()
        await await_until(
            lambda: views_converged([seed, a], 3) and len(b.members()) == 3,
            timeout=15,
        )
    finally:
        await shutdown_all(*clusters)


@pytest.mark.asyncio
async def test_double_partition_and_heal():
    """Split {a, b} vs {c, d}: each half removes the other after the
    suspicion timeout, and healing restores the full 4-view on every node
    (the double-partition case of MembershipProtocolTest.java:94-263)."""
    a = await start_node()
    b = await start_node(seeds=(a.address,))
    c = await start_node(seeds=(a.address,))
    d = await start_node(seeds=(a.address,))
    nodes = [a, b, c, d]
    try:
        await await_until(lambda: views_converged(nodes, 4), timeout=10)
        left, right = [a, b], [c, d]
        for u in left:
            for v in right:
                u.network_emulator.block_outbound(v.address)
                v.network_emulator.block_outbound(u.address)
        settle = suspicion_settle_time(4)
        await await_until(
            lambda: all(len(u.members()) == 2 for u in nodes),
            timeout=settle + 10,
        )
        left_ids = {a.member().id, b.member().id}
        right_ids = {c.member().id, d.member().id}
        assert {m.id for m in a.members()} == left_ids
        assert {m.id for m in c.members()} == right_ids
        for u in nodes:
            u.network_emulator.unblock_all()
        await await_until(lambda: views_converged(nodes, 4), timeout=20)
    finally:
        await shutdown_all(*nodes)


@pytest.mark.asyncio
async def test_all_nodes_lose_outbound_then_recover():
    """EVERY node blocks all outbound: each keeps itself trusted, suspects
    both peers, and nobody is removed before the links heal; unblocking
    clears every suspicion (MembershipProtocolTest.java:266-319)."""
    # Long suspicion timeout so the blackout phase cannot progress to
    # removal on a slow machine — the scenario is suspect-then-recover.
    cfg = lambda: fast_test_config().membership(
        lambda m: m.with_(suspicion_mult=40)
    )
    a = await start_node(cfg())
    b = await start_node(cfg(), seeds=(a.address,))
    c = await start_node(cfg(), seeds=(a.address,))
    nodes = [a, b, c]
    try:
        await await_until(lambda: views_converged(nodes, 3), timeout=10)
        for u in nodes:
            u.network_emulator.block_all_outbound()
        await await_until(
            lambda: all(len(u.monitor().suspected_members) == 2 for u in nodes),
            timeout=10,
        )
        # Suspicion, not eviction: views still hold all three members.
        assert views_converged(nodes, 3)
        for u in nodes:
            u.network_emulator.unblock_all_outbound()
        await await_until(
            lambda: views_converged(nodes, 3)
            and all(not u.monitor().suspected_members for u in nodes),
            timeout=15,
        )
    finally:
        await shutdown_all(*nodes)


@pytest.mark.asyncio
async def test_no_inbound_partition_removed_then_inbound_recovers():
    """C blocks ALL inbound: its outbound SYNCs still reach the others but
    nothing gets back in, so both sides remove each other after the
    suspicion timeout (repeated one-way SYNCs must NOT re-admit C, because
    ADDED is metadata-fetch-gated and the fetch cannot reach C); restoring
    inbound heals the full 3-view on every node
    (MembershipProtocolTest.java:702-752)."""
    a = await start_node()
    b = await start_node(seeds=(a.address,))
    c = await start_node(seeds=(a.address,))
    nodes = [a, b, c]
    try:
        await await_until(lambda: views_converged(nodes, 3), timeout=10)
        c.network_emulator.block_all_inbound()
        settle = suspicion_settle_time(3)
        await await_until(
            lambda: len(a.members()) == 2
            and len(b.members()) == 2
            and len(c.members()) == 1,
            timeout=settle + 10,
        )
        c_id = c.member().id
        assert c_id in {m.id for m in a.monitor().removed_members}
        assert {m.id for m in c.monitor().removed_members} == {
            a.member().id,
            b.member().id,
        }
        # One-way SYNCs from C keep arriving the whole time; give them a
        # moment to prove they do not resurrect C without a metadata path.
        await asyncio.sleep(1.0)
        assert len(a.members()) == 2
        c.network_emulator.unblock_all_inbound()
        await await_until(lambda: views_converged(nodes, 3), timeout=20)
    finally:
        await shutdown_all(*nodes)


@pytest.mark.parametrize("direction", ["inbound", "outbound", "both"])
@pytest.mark.asyncio
async def test_pairwise_link_partition_does_not_evict(direction):
    """A broken B<->C link (inbound, outbound, or both at C) evicts nobody:
    ping-req relays through A keep the failure detector quiet and gossip/
    SYNC via A keeps all views complete
    (MembershipProtocolTest.java:754-843)."""
    a = await start_node()
    b = await start_node(seeds=(a.address,))
    c = await start_node(seeds=(a.address,))
    nodes = [a, b, c]
    try:
        await await_until(lambda: views_converged(nodes, 3), timeout=10)
        if direction in ("inbound", "both"):
            c.network_emulator.block_inbound(b.address)
        if direction in ("outbound", "both"):
            c.network_emulator.block_outbound(b.address)
        await asyncio.sleep(suspicion_settle_time(3))
        assert views_converged(nodes, 3), (
            f"pairwise {direction} block must not evict any member"
        )
    finally:
        await shutdown_all(*nodes)


@pytest.mark.asyncio
async def test_restart_stopped_members_on_new_ports():
    """Stop two members, restart them on fresh ports: the old identities are
    removed and the new ones join, converging to a full view of new ids
    (MembershipProtocolTest.java:374-452)."""
    a = await start_node()
    b = await start_node(seeds=(a.address,))
    c = await start_node(seeds=(a.address,))
    d = await start_node(seeds=(a.address,))
    live = [a, b, c, d]
    try:
        await await_until(lambda: views_converged([a, b, c, d], 4), timeout=10)
        old_ids = {c.member().id, d.member().id}
        await shutdown_all(c, d)
        live = [a, b]
        await await_until(
            lambda: len(a.members()) == 2 and len(b.members()) == 2, timeout=15
        )
        c2 = await start_node(seeds=(a.address,))
        live.append(c2)
        d2 = await start_node(seeds=(a.address,))
        live.append(d2)
        nodes = [a, b, c2, d2]
        await await_until(lambda: views_converged(nodes, 4), timeout=15)
        for u in nodes:
            ids = {m.id for m in u.members()}
            assert not (ids & old_ids), "old identities must stay removed"
            assert {c2.member().id, d2.member().id} <= ids
    finally:
        await shutdown_all(*live)


@pytest.mark.asyncio
async def test_failed_metadata_fetch_retried_by_later_sync():
    """A failed metadata fetch must leave no table trace, so a LATER record
    at the SAME incarnation re-triggers the fetch and the member becomes
    visible (the reference applies ALIVE records only in fetchMetadata's
    doOnSuccess, MembershipProtocolImpl.java:518-543 — regression test for
    the round-3 fix where a pre-fetch table write blocked every retry)."""
    # FD probing disabled (one-hour ping interval): C must stay a plain
    # ALIVE-at-incarnation-0 record everywhere, so D's admission can only
    # come from a retried fetch on a SAME-incarnation record — the exact
    # regression path (a SUSPECT rumor would route admission through the
    # refutation/incarnation-bump channel instead and mask it).
    cfg = lambda: fast_test_config().failure_detector(
        lambda f: f.with_(ping_interval=3_600_000)
    )
    a = await start_node(cfg())
    b = await start_node(cfg(), seeds=(a.address,))
    c = await start_node(cfg(), seeds=(a.address,), metadata={"who": "c"})
    live = [a, b, c]
    try:
        await await_until(lambda: views_converged([a, b, c], 3), timeout=10)
        # C goes inbound-dark BEFORE D exists: D's entire knowledge of C
        # arrives as same-incarnation records from A/B, and every metadata
        # fetch D sends C fails.
        c.network_emulator.block_all_inbound()
        d = await start_node(cfg(), seeds=(a.address,))
        live.append(d)
        await await_until(
            lambda: d.member_by_id(a.member().id) is not None
            and d.member_by_id(b.member().id) is not None,
            timeout=10,
        )
        await asyncio.sleep(1.5)  # several sync periods of failed fetches
        assert d.member_by_id(c.member().id) is None
        # Heal the metadata path: the next same-incarnation record from
        # A/B's SYNC must retry the fetch and admit C at D.
        c.network_emulator.unblock_all_inbound()
        await await_until(
            lambda: d.member_by_id(c.member().id) is not None, timeout=10
        )
        assert d.metadata(d.member_by_id(c.member().id)) == {"who": "c"}
    finally:
        await shutdown_all(*live)


@pytest.mark.asyncio
async def test_heterogeneous_fd_timings_stay_alive():
    """Nodes running different ping intervals/timeouts still converge with
    no false suspicion (FailureDetectorTest.java:149-177)."""
    slow = fast_test_config().failure_detector(
        lambda f: f.with_(ping_interval=500, ping_timeout=400)
    )
    fast = fast_test_config().failure_detector(
        lambda f: f.with_(ping_interval=100, ping_timeout=50)
    )
    a = await start_node(config=slow)
    b = await start_node(config=fast, seeds=(a.address,))
    c = await start_node(seeds=(a.address,))
    nodes = [a, b, c]
    try:
        await await_until(lambda: views_converged(nodes, 3), timeout=10)
        # Let several heterogeneous FD rounds elapse; nobody may get removed
        # or even suspected.
        await asyncio.sleep(2.0)
        assert views_converged(nodes, 3)
        for u in nodes:
            assert u.monitor().suspected_members == ()
    finally:
        await shutdown_all(*nodes)


@pytest.mark.asyncio
async def test_suspected_member_refutes_with_incarnation_bump():
    """A transient partition gets ``a`` suspected; when it heals before the
    suspicion deadline, ``a`` sees the SUSPECT rumor about itself, refutes by
    bumping its incarnation, and is never removed
    (MembershipProtocolImpl.java:549-569, 612-618)."""
    # Stretch the suspicion window so the heal always lands inside it.
    cfg = fast_test_config().membership(lambda m: m.with_(suspicion_mult=15))
    seed = await start_node(config=cfg)
    a = await start_node(config=cfg, seeds=(seed.address,))
    try:
        await await_until(lambda: views_converged([seed, a], 2), timeout=10)
        inc0 = a.monitor().incarnation
        a.network_emulator.block_all_outbound()
        a.network_emulator.block_all_inbound()
        await await_until(
            lambda: any(
                m.id == a.member().id for m in seed.monitor().suspected_members
            ),
            timeout=10,
        )
        a.network_emulator.unblock_all()
        # a learns of the suspicion (sync/gossip), refutes, seed re-ALIVEs it
        await await_until(lambda: a.monitor().incarnation > inc0, timeout=10)
        await await_until(
            lambda: not seed.monitor().suspected_members, timeout=10
        )
        assert len(seed.members()) == 2
        assert seed.member_by_id(a.member().id) is not None
    finally:
        await shutdown_all(seed, a)


@pytest.mark.asyncio
async def test_restart_same_port_swaps_identity():
    """A member restarted on the same port joins with a new id; the old id is
    removed (MembershipProtocolTest.java:374-520)."""
    seed = await start_node()
    a = await start_node(seeds=(seed.address,))
    try:
        await await_until(lambda: views_converged([seed, a], 2), timeout=10)
        old_id = a.member().id
        port = a.member().address.port
        await a.shutdown()
        cfg = fast_test_config().transport(lambda t: t.with_(port=port))
        a2 = await start_node(config=cfg, seeds=(seed.address,))
        await await_until(
            lambda: seed.member_by_id(a2.member().id) is not None
            and seed.member_by_id(old_id) is None,
            timeout=suspicion_settle_time(2) + 5,
        )
        assert a2.member().address.port == port
        assert old_id in {m.id for m in seed.monitor().removed_members}
        await shutdown_all(a2)
    finally:
        await shutdown_all(seed, a)


@pytest.mark.asyncio
async def test_sync_group_isolation():
    """Nodes in different sync groups ignore each other's SYNCs even when
    seeded at each other (ClusterJoinExamples syncGroup isolation;
    MembershipProtocolImpl.java:442-448)."""
    seed = await start_node()
    outsider_cfg = fast_test_config().membership(
        lambda m: m.with_(sync_group="other-group")
    )
    outsider = await start_node(config=outsider_cfg, seeds=(seed.address,))
    try:
        await asyncio.sleep(2.0)
        assert len(seed.members()) == 1
        assert len(outsider.members()) == 1
    finally:
        await shutdown_all(seed, outsider)


@pytest.mark.asyncio
async def test_graceful_leave_observed_without_suspicion_delay():
    """Shutdown spreads a self-DEAD rumor: peers remove the leaver quickly,
    not after the suspicion timeout (ClusterTest.java:358-399)."""
    seed = await start_node()
    a = await start_node(seeds=(seed.address,))
    b = await start_node(seeds=(seed.address,))
    try:
        await await_until(lambda: views_converged([seed, a, b], 3), timeout=10)
        t0 = asyncio.get_running_loop().time()
        await b.shutdown()
        await await_until(
            lambda: len(seed.members()) == 2 and len(a.members()) == 2, timeout=5
        )
        elapsed = asyncio.get_running_loop().time() - t0
        # well under the ~2s suspicion route for this config
        assert elapsed < suspicion_settle_time(3)
    finally:
        await shutdown_all(seed, a, b)


@pytest.mark.asyncio
async def test_metadata_update_emits_updated_event():
    """update_metadata bumps incarnation and propagates UPDATED with old and
    new metadata (ClusterTest.java:117-273)."""
    seed = await start_node(metadata={"v": 1})
    a = await start_node(seeds=(seed.address,))
    try:
        await await_until(lambda: views_converged([seed, a], 2), timeout=10)
        events = []

        async def watch():
            async for e in a.listen_membership():
                if e.is_updated:
                    events.append(e)
                    return

        task = asyncio.create_task(watch())
        await seed.update_metadata({"v": 2})
        await asyncio.wait_for(task, timeout=10)
        assert events[0].member.id == seed.member().id
        assert events[0].old_metadata == {"v": 1}
        assert events[0].new_metadata == {"v": 2}
        assert a.metadata(a.member_by_id(seed.member().id)) == {"v": 2}
    finally:
        await shutdown_all(seed, a)


@pytest.mark.asyncio
async def test_suspected_lists_in_monitor():
    """The monitor exposes suspected members while a partition lasts
    (MembershipProtocolImpl.java:732-791 MBean lists)."""
    seed = await start_node()
    a = await start_node(seeds=(seed.address,))
    try:
        await await_until(lambda: views_converged([seed, a], 2), timeout=10)
        a.network_emulator.block_all_outbound()
        a.network_emulator.block_all_inbound()
        await await_until(
            lambda: any(
                m.id == a.member().id for m in seed.monitor().suspected_members
            ),
            timeout=10,
        )
    finally:
        await shutdown_all(seed, a)


@pytest.mark.asyncio
async def test_external_address_override_advertised():
    """memberHost/memberPort override what the local member advertises
    (ClusterImpl.java:277-288; MembershipProtocolTest.java:555-595)."""
    cfg = fast_test_config(external_host="10.10.10.10", external_port=4242)
    node = await start_node(cfg)
    try:
        assert node.member().address.host == "10.10.10.10"
        assert node.member().address.port == 4242
    finally:
        await shutdown_all(node)


@pytest.mark.asyncio
async def test_asymmetric_inbound_block_recovers():
    """Blocking only B's INBOUND links makes the others suspect it while its
    own outbound sync keeps fighting back; unblocking restores full views
    (the asymmetric scenarios of MembershipProtocolTest.java:598-918)."""
    seed = await start_node()
    a = await start_node(seeds=(seed.address,))
    b = await start_node(seeds=(seed.address,))
    try:
        await await_until(lambda: views_converged([seed, a, b], 3), timeout=10)

        b.network_emulator.block_all_inbound()
        await await_until(
            lambda: len(seed.monitor().suspected_members) > 0, timeout=10
        )

        b.network_emulator.unblock_all_inbound()
        await await_until(
            lambda: views_converged([seed, a, b], 3)
            and not seed.monitor().suspected_members,
            timeout=15,
        )
    finally:
        await shutdown_all(seed, a, b)


@pytest.mark.asyncio
async def test_removed_history_ring():
    """Removed members are retained in the monitor's bounded history ring
    (MembershipProtocolImpl.java:732-791 keeps the last 42)."""
    seed = await start_node()
    a = await start_node(seeds=(seed.address,))
    try:
        await await_until(lambda: views_converged([seed, a], 2), timeout=10)
        gone_id = a.member().id
        await a.shutdown()
        await await_until(
            lambda: gone_id in {m.id for m in seed.monitor().removed_members},
            timeout=10,
        )
        assert len(seed.monitor().removed_members) <= 42
    finally:
        await shutdown_all(seed, a)
