"""Sim-vs-host backend validation (SURVEY.md §7 stage 6).

The acceptance shape comes from GossipProtocolTest.java:154-203: complete
dissemination within the sweep deadline, measured curves logged against the
analytic prediction. Here the assertion is cross-BACKEND: the TPU sim and the
asyncio-TCP host runtime must produce matching dissemination dynamics for the
same protocol constants, which is the BASELINE.json north-star check
("convergence curves matching a Netty-backend run").

Tolerances: both backends are stochastic (independent RNGs, real sockets on
the host side), so trials are averaged and completion periods are compared
within a small window rather than bit-exactly.
"""

import numpy as np
import pytest

from scalecube_cluster_tpu import cluster_math
from scalecube_cluster_tpu.testlib.crossval import (
    compare_dissemination,
    sim_dissemination_curve,
)
from scalecube_cluster_tpu.testlib.fixtures import fast_test_config


@pytest.mark.asyncio
async def test_dissemination_matches_host_clean_network():
    n, periods = 12, 16
    result = await compare_dissemination(n, loss_percent=0.0, periods=periods)
    host, sim = result["host"], result["sim"]
    assert host.completion_period is not None, host.coverage
    assert sim.completion_period is not None, sim.coverage
    # Same dissemination speed: full coverage within a 3-period window.
    assert abs(host.completion_period - sim.completion_period) <= 3, result
    # Curves track each other on average.
    assert result["mean_abs_gap"] <= 0.15, result


@pytest.mark.asyncio
async def test_dissemination_matches_host_lossy_network():
    n, periods = 10, 24
    result = await compare_dissemination(n, loss_percent=25.0, periods=periods)
    host, sim = result["host"], result["sim"]
    assert host.completion_period is not None, host.coverage
    assert sim.completion_period is not None, sim.coverage
    assert abs(host.completion_period - sim.completion_period) <= 4, result
    assert result["mean_abs_gap"] <= 0.2, result


def test_sim_dissemination_tracks_cluster_math():
    """The sim's dissemination time obeys the ClusterMath estimate that the
    reference logs its measurements against (GossipProtocolTest.java:176-203,
    ClusterMath.java:77-79)."""
    cfg = fast_test_config()
    n = 50
    curve = sim_dissemination_curve(n, loss_percent=0.0, periods=40, trials=3)
    assert curve.completion_period is not None
    expected = cluster_math.gossip_periods_to_spread(
        cfg.gossip_config.gossip_repeat_mult, n
    )
    # Complete within the spread deadline, and not suspiciously instant.
    assert curve.completion_period <= expected
    assert curve.completion_period >= np.log2(n) - 2
