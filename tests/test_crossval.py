"""Sim-vs-host backend validation (SURVEY.md §7 stage 6).

The acceptance shape comes from GossipProtocolTest.java:154-203: complete
dissemination within the sweep deadline, measured curves logged against the
analytic prediction. Here the assertion is cross-BACKEND: the TPU sim and the
asyncio-TCP host runtime must produce matching dissemination dynamics for the
same protocol constants, which is the BASELINE.json north-star check
("convergence curves matching a Netty-backend run").

Tolerances: both backends are stochastic (independent RNGs, real sockets on
the host side), so trials are averaged and completion periods are compared
within a small window rather than bit-exactly. The period-indexed mesh
comparison asserts aligned coverage gap <= 5% and message counts within 10%.

The ±2% BASELINE aspiration HOLDS at scale **as a mean-gap statement**
(measured round 5, artifacts/crossval_r5.json via tools/crossval_100.py):
over a 5-setting grid on the reference's own axes (n∈{32,50},
loss∈{0,10,25}%, mean delay∈{0,2,100} ms — GossipProtocolTest.java:48-64),
averaging 50-100 independent host trials per setting, the EVENT-BINNED
mean gap (host infection wall-times re-binned onto the sim's x-axis
convention — no fitted alignment) is 0.23-0.45%, with sends ratios
1.02-1.04. Qualification (round-4 advisor): this is a mean over curves
whose tails saturate at 1.0 on both backends; the max per-period transient
gap is 1.7-7.1% against a per-period sampling SEM of 0.8-1.7%, reported
alongside in the artifact — the ±2% claim is NOT a pointwise bound. The
round-4 align_shift is retired: the measured median delivery lag behind
period boundaries (0.13-0.29 periods) shows boundary sampling trails event
binning by exactly one period, which is what the alignment search was
fitting. What remains in CI: (a) at CI trial counts (~3), the per-period
coverage std-error alone is 2-4%; (b) wall-clock period boundaries under
CI load — handled by the period-indexed x-axis plus the 0-2-period
alignment search (kept HERE because CI's 3 trials are too noisy for
event binning to pay); (c) loss draws are independent between backends by
design (<1%, irreducible). The 5% gate is therefore the tight-but-stable
envelope for CI, with the measured gap reported every run; the O(100)-
trial artifact is the ±2% evidence on record.
"""

import numpy as np
import pytest

from scalecube_cluster_tpu import cluster_math
from scalecube_cluster_tpu.testlib.crossval import (
    compare_dissemination,
    compare_gossip_mesh,
    sim_dissemination_curve,
)
from scalecube_cluster_tpu.testlib.fixtures import fast_test_config


@pytest.mark.asyncio
async def test_dissemination_matches_host_clean_network():
    n, periods = 12, 16
    # The host curve is wall-clock-timed over real sockets; on a loaded
    # single-core machine gossip periods stretch and the curve decouples
    # from the dynamics being validated. One retry absorbs that scheduling
    # artifact without weakening the property (both attempts run the full
    # comparison against the same bars).
    def curves_match(result) -> bool:
        # Same dissemination speed: full coverage within a 3-period window,
        # and curves tracking each other on average. ONE definition of the
        # bar, shared by the retry gate and the final assertion.
        host, sim = result["host"], result["sim"]
        return (
            host.completion_period is not None
            and sim.completion_period is not None
            and abs(host.completion_period - sim.completion_period) <= 3
            and result["mean_abs_gap"] <= 0.15
        )

    result = None
    for _attempt in range(2):
        result = await compare_dissemination(n, loss_percent=0.0, periods=periods)
        if curves_match(result):
            return
    assert curves_match(result), result


@pytest.mark.asyncio
@pytest.mark.parametrize("loss", [0.0, 25.0])
async def test_gossip_mesh_curves_and_counts_match(loss):
    """Round-2 tightened validation (VERDICT item 5): period-indexed,
    gossip-only comparison at n=32 with message-count parity.

    Measured on this box: aligned mean gap 1-3%, sends ratio within 2%
    (raw un-aligned gap 3-5%). What still blocks a flat ±2% on the RAW gap:
    the host's injection waits for its next period boundary and listener
    delivery adds sub-period latency, phase-shifting the host curve by up to
    two periods — a timing artifact of real sockets, not a dynamics
    difference, hence the aligned comparison (testlib/crossval.py).
    """
    n, periods = 32, 24 if loss == 0.0 else 30
    result = await compare_gossip_mesh(n, loss, periods, trials=3)
    host, sim = result["host"], result["sim"]
    assert host.completion_period is not None, host.coverage
    assert sim.completion_period is not None, sim.coverage
    assert abs(host.completion_period - sim.completion_period) <= 3, result
    # Tracked numbers (printed so every CI run records the actual gap).
    print(
        f"crossval n={n} loss={loss}: aligned_gap="
        f"{result['aligned_mean_gap']:.4f} (shift {result['align_shift']}) "
        f"raw_gap={result['mean_abs_gap']:.4f} "
        f"sends_ratio={result['sends_ratio']:.3f}"
    )
    assert result["aligned_mean_gap"] <= 0.05, result
    assert abs(result["sends_ratio"] - 1.0) <= 0.10, result


@pytest.mark.asyncio
async def test_dissemination_matches_host_lossy_network():
    n, periods = 10, 24
    result = await compare_dissemination(n, loss_percent=25.0, periods=periods)
    host, sim = result["host"], result["sim"]
    assert host.completion_period is not None, host.coverage
    assert sim.completion_period is not None, sim.coverage
    assert abs(host.completion_period - sim.completion_period) <= 4, result
    # Wall-clock-sampled full-cluster curve: loose tolerance (event-loop
    # load smears it); the tight assertion lives in the period-indexed
    # gossip-mesh test above.
    assert result["mean_abs_gap"] <= 0.25, result


def test_sim_dissemination_tracks_cluster_math():
    """The sim's dissemination time obeys the ClusterMath estimate that the
    reference logs its measurements against (GossipProtocolTest.java:176-203,
    ClusterMath.java:77-79)."""
    cfg = fast_test_config()
    n = 50
    curve = sim_dissemination_curve(n, loss_percent=0.0, periods=40, trials=3)
    assert curve.completion_period is not None
    expected = cluster_math.gossip_periods_to_spread(
        cfg.gossip_config.gossip_repeat_mult, n
    )
    # Complete within the spread deadline, and not suspiciously instant.
    assert curve.completion_period <= expected
    assert curve.completion_period >= np.log2(n) - 2


@pytest.mark.asyncio
async def test_scheduled_block_heal_counters_match():
    """Scheduled-fault crossval (ISSUE 4 satellite): the same block→heal
    timeline — partition node 0, then reconnect — run as emulator
    blockOutbound windows on the host and as ONE in-scan FaultSchedule on
    the sparse engine, produces matching drop-cause deltas: ``fault_blocked``
    accumulates only inside the block window on both backends, and
    ``fault_lost`` stays zero everywhere (deterministic blocks are not
    probabilistic loss). Absolute counts differ (traffic volumes do); the
    schema and the window placement are the cross-checked contract."""
    from scalecube_cluster_tpu.testlib.crossval import (
        compare_scheduled_block_counters,
    )

    result = await compare_scheduled_block_counters(
        n=8, block_rounds=5, heal_rounds=5
    )
    for side in ("host", "sim"):
        block, heal = result[side]["block"], result[side]["heal"]
        assert block["fault_blocked"] > 0, (side, result)
        assert heal["fault_blocked"] == 0, (side, result)
        assert block["fault_lost"] == 0, (side, result)
        assert heal["fault_lost"] == 0, (side, result)
    print(
        f"block/heal crossval n=8: host blocked={result['host']['block']['fault_blocked']} "
        f"sim blocked={result['sim']['block']['fault_blocked']}"
    )


@pytest.mark.asyncio
async def test_protocol_counters_match_host():
    """Cross-backend counter parity (ISSUE 2): both backends report the
    SHARED_COUNTERS schema, and on a clean network their FD cadence agrees
    — every fd period issues exactly one direct ping that gets acked, so
    pings/period and acks/period are ~1.0 on both sides, with zero
    suspicions or death verdicts. SYNC and gossip message counts are NOT
    asserted equal: the host runs full-table periodic SYNC pairs plus
    join-residual gossip, the sim a windowed SYNC — a documented cadence
    asymmetry, not a protocol divergence (testlib/crossval.py)."""
    from scalecube_cluster_tpu.obs.counters import SHARED_COUNTERS
    from scalecube_cluster_tpu.testlib.crossval import compare_protocol_counters

    result = await compare_protocol_counters(n=8, fd_rounds=6)
    host, sim = result["host"], result["sim"]
    assert result["host_keys_ok"], sorted(host["counters"])
    assert result["sim_keys_ok"], sorted(sim["counters"])
    assert set(result["schema_keys"]) == set(SHARED_COUNTERS)

    for side in (host, sim):
        assert side["counters"]["suspicions_raised"] == 0, side
        assert side["counters"]["verdicts_dead"] == 0, side
        assert side["fd_periods"] > 0, side

    # One direct ping per member per fd period, acked (clean network).
    # Tolerance absorbs boundary effects of wall-clock sampling on the
    # host side (a probe may straddle the measurement window).
    for rate_key in ("host_ping_rate", "sim_ping_rate", "host_ack_rate", "sim_ack_rate"):
        assert 0.7 <= result[rate_key] <= 1.2, (rate_key, result)
    print(
        f"counter crossval n=8: host pings/period={result['host_ping_rate']:.2f} "
        f"sim={result['sim_ping_rate']:.2f} host acks/period="
        f"{result['host_ack_rate']:.2f} sim={result['sim_ack_rate']:.2f}"
    )


def test_zone_model_composition_matches_sim_edge_helpers():
    """The host emulator's ZoneModel must compose zone overlays with the
    EXACT formulas the sim engines resolve per edge (sim/faults.py::
    edge_blocked / edge_loss / edge_mean_delay): OR for blocks,
    1-(1-p)(1-q) for independent drops, additive exponential means. A
    drawn 3-zone world over a lossy base plan is compared edge by edge —
    bit-level agreement on blocks, float tolerance on the composed
    loss/delay (the host computes in float64, the device in float32)."""
    import jax.numpy as jnp

    from scalecube_cluster_tpu.sim.faults import (
        FaultPlan,
        edge_blocked,
        edge_loss,
        edge_mean_delay,
    )
    from scalecube_cluster_tpu.sim.topology import LinkWorld
    from scalecube_cluster_tpu.testlib.network_emulator import (
        NetworkEmulator,
        ZoneModel,
    )
    from scalecube_cluster_tpu.utils.address import Address

    n = 12
    rng = np.random.default_rng(5)
    zone = rng.integers(0, 3, size=n).astype(np.int32)
    world = (
        LinkWorld.from_zones(zone, n_zones=3)
        .with_zone_latency(0, 1, 80.0)
        .with_zone_latency(1, 2, 400.0)
        .with_zone_loss(0, 2, 0.25)
        .block_zones(2, 0, symmetric=False)
    )
    plan = FaultPlan.uniform(loss_percent=10.0, mean_delay_ms=2.0)
    plan = plan.with_link_world(world)

    addresses = [Address("127.0.0.1", 20000 + i) for i in range(n)]
    model = ZoneModel.from_link_world(world, addresses)

    src = jnp.arange(n, dtype=jnp.int32)[:, None].repeat(n, axis=1)
    dst = jnp.arange(n, dtype=jnp.int32)[None, :].repeat(n, axis=0)
    sim_blk = np.asarray(edge_blocked(plan, src, dst))
    sim_loss = np.asarray(edge_loss(plan, src, dst))
    sim_delay = np.asarray(edge_mean_delay(plan, src, dst))

    for i in range(n):
        em = NetworkEmulator(addresses[i], seed=0)
        em.set_default_outbound_settings(10.0, 2.0)
        em.set_zone_model(model)
        for j in range(n):
            s = em.outbound_settings_of(addresses[j])
            assert s.blocked == bool(sim_blk[i, j]), (i, j)
            assert abs(s.loss_percent / 100.0 - float(sim_loss[i, j])) < 1e-6
            assert abs(s.mean_delay_ms - float(sim_delay[i, j])) < 1e-4
