"""The fused Pallas delivery kernel is bit-equivalent to the XLA path.

Runs interpreted on the CPU test backend (pallas_guide.md interpret mode);
the performance claim is validated on the TPU chip by bench.py with
SimParams.pallas_delivery=True.
"""

import dataclasses

import jax
import jax.numpy as jnp

from scalecube_cluster_tpu.ops.delivery import (
    fanout_permutations,
    permuted_delivery_two_channel,
)
from scalecube_cluster_tpu.ops.merge import is_alive_key
from scalecube_cluster_tpu.ops.pallas_delivery import (
    permuted_delivery_two_channel_pallas,
)
from scalecube_cluster_tpu.sim import FaultPlan, init_full_view, kill, run_ticks
from scalecube_cluster_tpu.sim.state import seeds_mask
from tests.test_sim import small_params


def test_kernel_matches_xla_path():
    n, m, f = 96, 80, 3
    rows = jax.random.randint(jax.random.PRNGKey(0), (n, m), -1, 1 << 22, jnp.int32)
    # Include rows of pure -1 (nothing to send) and full edges-off columns.
    rows = rows.at[5].set(-1)
    _, inv = fanout_permutations(jax.random.PRNGKey(1), n, f)
    ok = jax.random.bernoulli(jax.random.PRNGKey(2), 0.7, (f, n))
    ok = ok.at[:, 9].set(False)

    a_ref, b_ref = permuted_delivery_two_channel(rows, is_alive_key, inv, ok)
    a_ker, b_ker = permuted_delivery_two_channel_pallas(rows, inv, ok)
    assert bool(jnp.all(a_ref == a_ker))
    assert bool(jnp.all(b_ref == b_ker))


def test_sim_tick_equal_with_kernel():
    """Whole-tick trajectories agree between delivery implementations."""
    n = 32
    p = small_params(n)
    p_pallas = dataclasses.replace(p, pallas_delivery=True)
    plan, sm = FaultPlan.clean(n).with_loss(10.0), seeds_mask(n, [0])

    st = kill(init_full_view(n, user_gossip_slots=2, seed=11), 3)
    ref, tr_ref = run_ticks(p, st, plan, sm, 25)

    st = kill(init_full_view(n, user_gossip_slots=2, seed=11), 3)
    out, tr_ker = run_ticks(p_pallas, st, plan, sm, 25)

    assert bool(jnp.all(ref.view == out.view))
    assert bool(jnp.all(tr_ref["convergence"] == tr_ker["convergence"]))
