"""ops/ kernels pinned to the scalar reference semantics.

The decisive test is the exhaustive cross-check of the vectorized lattice
(ops/merge.py) against the scalar ``is_overrides`` (MembershipRecord.java:66-84
truth table, already pinned by test_membership_record.py) over every
(status, incarnation) pair combination.
"""

import itertools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from scalecube_cluster_tpu.cluster_api.member import Member, MemberStatus
from scalecube_cluster_tpu.cluster_api.membership_record import (
    MembershipRecord,
    is_overrides,
)
from scalecube_cluster_tpu.ops import (
    UNKNOWN_KEY,
    decode_epoch,
    decode_incarnation,
    decode_status,
    deliver_rows_any,
    deliver_rows_max,
    encode_key,
    is_alive_key,
    masked_random_choice,
    masked_random_topk,
    merge_views,
    overrides_same_epoch,
)
from scalecube_cluster_tpu.utils.address import Address

STATUSES = [MemberStatus.ALIVE, MemberStatus.SUSPECT, MemberStatus.DEAD]
INCS = [0, 1, 2, 7]

_MEMBER = Member(id="m", address=Address.create("127.0.0.1", 1))


def _rec(status, inc):
    return MembershipRecord(member=_MEMBER, status=status, incarnation=inc)


# -- key codec ----------------------------------------------------------------


def test_encode_decode_roundtrip():
    statuses, incs, epochs = [], [], []
    for s in STATUSES:
        for inc in INCS:
            for ep in (0, 1, 5):
                statuses.append(int(s))
                incs.append(inc)
                epochs.append(ep)
    key = encode_key(jnp.array(statuses), jnp.array(incs), jnp.array(epochs))
    np.testing.assert_array_equal(decode_status(key), np.array(statuses))
    np.testing.assert_array_equal(decode_incarnation(key), np.array(incs))
    np.testing.assert_array_equal(decode_epoch(key), np.array(epochs))


def test_unknown_encodes_to_sentinel():
    key = encode_key(jnp.array([int(MemberStatus.UNKNOWN)]), jnp.array([5]))
    assert int(key[0]) == UNKNOWN_KEY
    assert int(decode_status(key)[0]) == int(MemberStatus.UNKNOWN)
    assert not bool(is_alive_key(key)[0])


def test_is_alive_key():
    key = encode_key(
        jnp.array([int(s) for s in STATUSES]), jnp.array([3, 3, 3])
    )
    np.testing.assert_array_equal(
        np.asarray(is_alive_key(key)), [True, False, False]
    )


# -- override lattice vs scalar truth table -----------------------------------


def test_overrides_matches_scalar_exhaustively():
    """Every same-epoch (r1, r0) pair must agree with scalar is_overrides."""
    pairs = list(
        itertools.product(
            itertools.product(STATUSES, INCS), itertools.product(STATUSES, INCS)
        )
    )
    s1 = jnp.array([int(p[0][0]) for p in pairs])
    i1 = jnp.array([p[0][1] for p in pairs])
    s0 = jnp.array([int(p[1][0]) for p in pairs])
    i0 = jnp.array([p[1][1] for p in pairs])
    got = np.asarray(overrides_same_epoch(encode_key(s1, i1), encode_key(s0, i0)))
    want = np.array(
        [is_overrides(_rec(p[0][0], p[0][1]), _rec(p[1][0], p[1][1])) for p in pairs]
    )
    np.testing.assert_array_equal(got, want)


def test_overrides_unknown_introduction_via_merge():
    """r0=None: only ALIVE introduces (membership_record.py is_overrides)."""
    local = jnp.full((3,), UNKNOWN_KEY, jnp.int32)
    incoming = encode_key(
        jnp.array([int(s) for s in STATUSES]), jnp.array([5, 5, 5])
    )
    best_alive = jnp.where(is_alive_key(incoming), incoming, UNKNOWN_KEY)
    merged, changed = merge_views(local, incoming, best_alive)
    # ALIVE introduced; SUSPECT and DEAD rumors about unknown members dropped.
    np.testing.assert_array_equal(np.asarray(changed), [True, False, False])
    assert int(decode_status(merged)[0]) == int(MemberStatus.ALIVE)
    assert int(merged[1]) == UNKNOWN_KEY and int(merged[2]) == UNKNOWN_KEY


def test_merge_epoch_rules():
    alive, suspect, dead = (
        int(MemberStatus.ALIVE),
        int(MemberStatus.SUSPECT),
        int(MemberStatus.DEAD),
    )
    # local: epoch-0 DEAD (sticky) | epoch-0 ALIVE | epoch-1 ALIVE inc=4
    local = encode_key(
        jnp.array([dead, alive, alive]),
        jnp.array([3, 3, 4]),
        jnp.array([0, 0, 1]),
    )
    # incoming: epoch-1 ALIVE (restart) | epoch-0 SUSPECT same inc | stale epoch-0
    best_any = encode_key(
        jnp.array([alive, suspect, suspect]),
        jnp.array([0, 3, 9]),
        jnp.array([1, 0, 0]),
    )
    best_alive = jnp.where(is_alive_key(best_any), best_any, UNKNOWN_KEY)
    merged, changed = merge_views(local, best_any, best_alive)
    # restart epoch supersedes sticky dead of the previous generation
    assert int(decode_epoch(merged)[0]) == 1
    assert int(decode_status(merged)[0]) == alive
    # same-epoch SUSPECT overrides ALIVE at equal incarnation
    assert int(decode_status(merged)[1]) == suspect
    # stale lower-epoch rumor dropped
    assert not bool(changed[2])


def test_merge_dead_epoch_cannot_introduce():
    """A newer-epoch SUSPECT/DEAD rumor must not introduce the identity."""
    alive, dead = int(MemberStatus.ALIVE), int(MemberStatus.DEAD)
    local = encode_key(jnp.array([alive]), jnp.array([7]), jnp.array([0]))
    best_any = encode_key(jnp.array([dead]), jnp.array([0]), jnp.array([1]))
    best_alive = jnp.full((1,), UNKNOWN_KEY, jnp.int32)
    merged, changed = merge_views(local, best_any, best_alive)
    assert not bool(changed[0])
    assert int(decode_epoch(merged)[0]) == 0


# -- delivery scatter ---------------------------------------------------------


def test_deliver_rows_max_combines_and_drops():
    rows = jnp.array(
        [[5, -1], [3, 9], [-1, 7], [1, 1]], jnp.int32
    )  # sender payloads
    dst = jnp.array([[2, 3], [2, 0], [0, 1], [0, 0]], jnp.int32)
    edge_ok = jnp.array(
        [[True, True], [True, True], [True, False], [False, False]]
    )
    got = np.asarray(deliver_rows_max(rows, dst, edge_ok, 4))
    # receiver 0: from sender1 (ack edge) and sender2 -> max([3,9],[-1,7])
    np.testing.assert_array_equal(got[0], [3, 9])
    # receiver 1: sender2's second edge is dropped
    np.testing.assert_array_equal(got[1], [-1, -1])
    # receiver 2: senders 0 and 1
    np.testing.assert_array_equal(got[2], [5, 9])
    # receiver 3: sender 0 only
    np.testing.assert_array_equal(got[3], [5, -1])


def test_deliver_rows_any():
    flags = jnp.array([[True, False], [False, True]])
    dst = jnp.array([[1], [0]], jnp.int32)
    edge_ok = jnp.array([[True], [False]])
    got = np.asarray(deliver_rows_any(flags, dst, edge_ok, 2))
    np.testing.assert_array_equal(got, [[False, False], [True, False]])


# -- selection ----------------------------------------------------------------


def test_masked_topk_distinct_and_valid():
    rng = jax.random.PRNGKey(0)
    n = 16
    mask = jnp.ones((8, n), bool).at[:, 0].set(False)
    mask = mask & ~jnp.eye(8, n, dtype=bool)
    idx, valid = masked_random_topk(rng, mask, 3)
    assert bool(valid.all())
    idx = np.asarray(idx)
    for row, picks in enumerate(idx):
        assert len(set(picks.tolist())) == 3  # distinct
        assert 0 not in picks and row not in picks  # respects mask


def test_masked_topk_undersized_candidate_set():
    mask = jnp.zeros((2, 4), bool).at[0, 2].set(True)
    _, valid = masked_random_topk(jax.random.PRNGKey(1), mask, 3)
    assert int(valid[0].sum()) == 1 and int(valid[1].sum()) == 0


def test_masked_choice_uniformity():
    rng = jax.random.PRNGKey(42)
    mask = jnp.ones((4000, 8), bool).at[:, 3].set(False)
    idx, valid = masked_random_choice(rng, mask)
    assert bool(valid.all())
    counts = np.bincount(np.asarray(idx), minlength=8)
    assert counts[3] == 0
    # each of the 7 candidates ~ 4000/7 ≈ 571; loose 4-sigma band
    assert counts[counts > 0].min() > 450 and counts.max() < 700


@pytest.mark.parametrize("k", [1, 3])
def test_topk_jit_compatible(k):
    mask = jnp.ones((4, 6), bool)
    f = jax.jit(lambda r, m: masked_random_topk(r, m, k))
    idx, valid = f(jax.random.PRNGKey(0), mask)
    assert idx.shape == (4, k)


def test_perm_from_structured_inverts_inv():
    """perm_from_structured is the closed-form inverse of the structured
    fan-out draw (ops/delivery.py): perm[c, inv[c, j]] == j for every
    channel, receiver, and group size — the property the gather-free
    suppression check in user_gossip_step_tracked rests on."""
    import jax

    from scalecube_cluster_tpu.ops.delivery import (
        fanout_permutations_structured,
        perm_from_structured,
    )

    for group, n in ((8, 64), (32, 256)):
        inv, ginv, rots = fanout_permutations_structured(
            jax.random.PRNGKey(3), n, 3, group=group
        )
        perm = perm_from_structured(ginv, rots, n, group=group)
        j = jnp.arange(n)
        for c in range(3):
            assert jnp.array_equal(perm[c][inv[c]], j)
            assert jnp.array_equal(inv[c][perm[c]], j)


def test_tracked_user_gossip_perm_arg_is_bit_invisible():
    """user_gossip_step_tracked(perm=...) must equal the perm=None
    (argsort fallback) path bit-for-bit — same sends, same ring writes."""
    import jax

    from scalecube_cluster_tpu.ops.delivery import (
        fanout_permutations_structured,
        perm_from_structured,
    )
    from scalecube_cluster_tpu.sim.usergossip import user_gossip_step_tracked

    n, G, K, f = 64, 3, 4, 3
    ks = jax.random.split(jax.random.PRNGKey(7), 6)
    useen = jax.random.bernoulli(ks[0], 0.4, (n, G))
    uage = jax.random.randint(ks[1], (n, G), 0, 20)
    uinf = jax.random.randint(ks[2], (n, G, K), -1, n)
    uptr = jax.random.randint(ks[3], (n, G), 0, K)
    inv, ginv, rots = fanout_permutations_structured(ks[4], n, f, group=8)
    edge_ok = jax.random.bernoulli(ks[5], 0.9, (f, n))
    alive = jnp.ones((n,), bool).at[5].set(False)
    args = (useen, uage, uinf, uptr, inv, edge_ok, alive, 8, 18)
    ref = user_gossip_step_tracked(*args)
    out = user_gossip_step_tracked(
        *args, perm=perm_from_structured(ginv, rots, n, group=8)
    )
    for a, b in zip(ref, out):
        assert jnp.array_equal(a, b)
