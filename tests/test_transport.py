"""TCP transport integration tests.

Ports the observable semantics of TransportTest.java:42-341 and
TransportSendOrderTest.java:41-207 onto the asyncio backend: loopback
ping-pong request/response, connect-failure propagation, per-connection FIFO
ordering, listen() completion on stop, and subscriber isolation.
"""

import asyncio
import dataclasses

import pytest

from scalecube_cluster_tpu import Address
from scalecube_cluster_tpu.cluster_api.config import TransportConfig
from scalecube_cluster_tpu.transport import (
    JsonMessageCodec,
    Message,
    TcpTransport,
    register_data_type,
)


async def bind() -> TcpTransport:
    return await TcpTransport.bind(TransportConfig(connect_timeout=1000))


async def echo_server(transport: TcpTransport) -> asyncio.Task:
    """Reply to every inbound message over the wire, echoing cid."""

    async def serve():
        async for msg in transport.listen():
            reply = msg.with_data(("echo", msg.data)).with_sender(transport.address)
            await transport.send(msg.sender, reply)

    return asyncio.create_task(serve())


@pytest.mark.asyncio
async def test_ping_pong_request_response():
    a, b = await bind(), await bind()
    server = await echo_server(b)
    try:
        req = Message.create(
            qualifier="hi", data="ping", correlation_id="cid-1", sender=a.address
        )
        resp = await a.request_response(b.address, req, timeout=2)
        # Tuples round-trip as tuples over the wire (tagged in the codec).
        assert resp.data == ("echo", "ping")
        assert resp.correlation_id == "cid-1"
    finally:
        server.cancel()
        await a.stop()
        await b.stop()


@pytest.mark.asyncio
async def test_send_to_unreachable_fails():
    a = await bind()
    try:
        dead = Address("127.0.0.1", 1)  # nothing listens on port 1
        with pytest.raises((ConnectionError, OSError, asyncio.TimeoutError)):
            await a.send(dead, Message.create(qualifier="x", sender=a.address))
    finally:
        await a.stop()


@pytest.mark.asyncio
async def test_send_to_unresolved_host_fails():
    """A hostname that cannot resolve surfaces as an error on the send path,
    not a hang (TransportTest.java:43-55)."""
    a = await bind()
    try:
        ghost = Address("wrong-host.invalid", 5000)  # RFC 2606 reserved TLD
        with pytest.raises((ConnectionError, OSError, asyncio.TimeoutError)):
            await a.send(ghost, Message.create(qualifier="x", sender=a.address))
    finally:
        await a.stop()


@pytest.mark.asyncio
async def test_request_response_timeout():
    a, b = await bind(), await bind()  # b never answers
    try:
        req = Message.create(qualifier="q", correlation_id="c-1", sender=a.address)
        with pytest.raises(asyncio.TimeoutError):
            await a.request_response(b.address, req, timeout=0.2)
    finally:
        await a.stop()
        await b.stop()


@pytest.mark.asyncio
async def test_per_connection_fifo_order():
    """TransportSendOrderTest.java:41-207 — single cached connection keeps FIFO."""
    a, b = await bind(), await bind()
    try:
        n = 200
        stream = b.listen()
        for i in range(n):
            await a.send(
                b.address, Message.create(qualifier="seq", data=i, sender=a.address)
            )
        received = []
        async def collect():
            async for msg in stream:
                received.append(msg.data)
                if len(received) == n:
                    return
        await asyncio.wait_for(collect(), timeout=5)
        assert received == list(range(n))
    finally:
        await a.stop()
        await b.stop()


@pytest.mark.asyncio
async def test_concurrent_senders_fifo_order():
    """TransportSendOrderTest.java:41-207, multi-threaded-sender case: several
    concurrent senders share the one cached connection; each sender's own
    sequence must arrive in order (interleaving between senders is free)."""
    a, b = await bind(), await bind()
    try:
        n_senders, n_msgs = 8, 100
        stream = b.listen()

        async def sender(tag: int):
            for i in range(n_msgs):
                await a.send(
                    b.address,
                    Message.create(qualifier="seq", data=(tag, i), sender=a.address),
                )

        received: list[tuple[int, int]] = []

        async def collect():
            async for msg in stream:
                received.append(msg.data)
                if len(received) == n_senders * n_msgs:
                    return

        collector = asyncio.create_task(collect())
        await asyncio.gather(*(sender(t) for t in range(n_senders)))
        await asyncio.wait_for(collector, timeout=10)
        for tag in range(n_senders):
            seq = [i for t, i in received if t == tag]
            assert seq == list(range(n_msgs)), f"sender {tag} out of order"
    finally:
        await a.stop()
        await b.stop()


@pytest.mark.asyncio
async def test_listen_completes_on_stop():
    """TransportTest.java:242-265 — listen() streams end when transport stops."""
    a = await bind()
    stream = a.listen()

    async def drain():
        return [m async for m in stream]

    task = asyncio.create_task(drain())
    await asyncio.sleep(0.05)
    await a.stop()
    assert await asyncio.wait_for(task, timeout=2) == []


@pytest.mark.asyncio
async def test_stop_drains_inflight_frames():
    """stop() must DRAIN accepted connections, not cancel them mid-frame:
    frames a client already put on the wire are decoded and dispatched
    before the listen() streams complete (the serving bridge's shutdown
    contract, serve/ingest.py::TcpEventSource). Written with a raw socket
    and no yield between the writes and stop() so only the drain path —
    never scheduler luck — can deliver the frames."""
    b = await bind()
    stream = b.listen()

    async def drain():
        return [m.data async for m in stream]

    task = asyncio.create_task(drain())
    reader, writer = await asyncio.open_connection(b.address.host, b.address.port)
    try:
        await asyncio.sleep(0.05)  # server-side handler is accepted + reading
        n = 5
        for i in range(n):
            payload = b._codec.serialize(Message.create(qualifier="serve/event", data=i))
            writer.write(b._encode(payload, b._config.max_frame_length))
        # No await between the writes and stop(): the frames are in flight.
        await b.stop()
        got = await asyncio.wait_for(task, timeout=2)
        assert got == list(range(n))
    finally:
        writer.close()


@pytest.mark.asyncio
async def test_stop_bounded_with_idle_peer_connection():
    """A peer holding its connection open and idle must not stall stop()
    past the drain grace (and must never deadlock Python 3.12's
    wait_closed): the accepted socket is EOF'd and its handler exits."""
    b = await TcpTransport.bind(
        TransportConfig(connect_timeout=1000, stop_drain_ms=200)
    )
    a = await bind()
    try:
        # Open (and keep open) a connection into b's listener.
        await a.send(
            b.address, Message.create(qualifier="x", data=0, sender=a.address)
        )
        await asyncio.sleep(0.05)
        await asyncio.wait_for(b.stop(), timeout=2)
    finally:
        await a.stop()


@pytest.mark.asyncio
async def test_subscriber_isolation():
    """TransportTest.java:268-313 — a failing subscriber doesn't affect others."""
    a, b = await bind(), await bind()
    try:
        good = b.listen()
        bad = b.listen()

        async def bad_consumer():
            async for _ in bad:
                raise RuntimeError("subscriber blew up")

        bad_task = asyncio.create_task(bad_consumer())
        for i in range(3):
            await a.send(
                b.address, Message.create(qualifier="x", data=i, sender=a.address)
            )
        got = []
        async def collect():
            async for m in good:
                got.append(m.data)
                if len(got) == 3:
                    return
        await asyncio.wait_for(collect(), timeout=2)
        assert got == [0, 1, 2]
        with pytest.raises(RuntimeError):
            await bad_task
    finally:
        await a.stop()
        await b.stop()


@pytest.mark.asyncio
async def test_oversized_frame_rejected_on_send():
    a = await bind()
    small = await TcpTransport.bind(TransportConfig(max_frame_length=64))
    try:
        big = Message.create(qualifier="big", data="x" * 1000, sender=small.address)
        with pytest.raises(ValueError):
            await small.send(a.address, big)
    finally:
        await a.stop()
        await small.stop()


@pytest.mark.asyncio
async def test_failed_dial_evicted_and_backoff_counted():
    """A failed connect leaves no future in the cache (a poisoned entry
    would fail every later send to that address without redialing), and
    consecutive failures advance the reconnect-backoff counter."""
    a = await TcpTransport.bind(
        TransportConfig(connect_timeout=1000, reconnect_backoff_min_ms=1)
    )
    try:
        dead = Address("127.0.0.1", 1)  # nothing listens on port 1
        for expected_failures in (1, 2):
            with pytest.raises((ConnectionError, OSError, asyncio.TimeoutError)):
                await a.send(dead, Message.create(qualifier="x", sender=a.address))
            assert dead not in a._connections
            assert a._dial_failures[dead] == expected_failures
    finally:
        await a.stop()


@pytest.mark.asyncio
async def test_closing_writer_evicted_and_redialed():
    """A cached connection whose writer is shutting down (peer died; the
    reader task hasn't evicted yet) is dropped at lookup and the send
    succeeds over a fresh dial instead of writing into the closing socket."""
    a, b = await bind(), await bind()
    got = []

    async def collect():
        async for msg in b.listen():
            got.append(msg.data)

    task = asyncio.create_task(collect())
    try:
        await a.send(b.address, Message.create(qualifier="x", data=1, sender=a.address))
        stale_fut = a._connections[b.address]
        stale_fut.result().writer.close()  # simulate peer-side shutdown
        await a.send(b.address, Message.create(qualifier="x", data=2, sender=a.address))
        assert a._connections[b.address] is not stale_fut
        await asyncio.sleep(0.1)
        assert got == [1, 2]
    finally:
        task.cancel()
        await a.stop()
        await b.stop()


@pytest.mark.asyncio
async def test_successful_connect_resets_backoff():
    a, b = await bind(), await bind()
    server = await echo_server(b)
    try:
        a._dial_failures[b.address] = 5  # as if earlier dials failed
        # Keep the pre-dial backoff sleep short for the test.
        a._config = dataclasses.replace(
            a._config, reconnect_backoff_min_ms=1, reconnect_backoff_max_ms=2
        )
        req = Message.create(
            qualifier="hi", data="ping", correlation_id="c-7", sender=a.address
        )
        resp = await a.request_response(b.address, req, timeout=2)
        assert resp.data == ("echo", "ping")
        assert b.address not in a._dial_failures
    finally:
        server.cancel()
        await a.stop()
        await b.stop()


def test_backoff_delay_bounded_with_jitter():
    """The redial delay grows exponentially from min to max and stays inside
    the jitter envelope at every attempt (never negative, never unbounded)."""
    cfg = TransportConfig(
        reconnect_backoff_min_ms=50,
        reconnect_backoff_max_ms=2_000,
        reconnect_backoff_jitter=0.2,
    )
    t = TcpTransport(cfg)
    assert t._backoff_delay(0) == 0.0
    for attempt in range(1, 40):
        lo = min(50 * 2 ** min(attempt - 1, 16), 2_000) / 1000.0
        for _ in range(8):
            d = t._backoff_delay(attempt)
            assert lo * 0.8 <= d <= lo * 1.2, (attempt, d)
    # Jitter off -> deterministic; min 0 -> disabled entirely.
    t0 = TcpTransport(dataclasses.replace(cfg, reconnect_backoff_jitter=0.0))
    assert t0._backoff_delay(3) == 0.2
    t_off = TcpTransport(dataclasses.replace(cfg, reconnect_backoff_min_ms=0))
    assert t_off._backoff_delay(10) == 0.0


# -- hostile wire input (ISSUE 12: the serving plane faces untrusted peers) --


def _valid_frame(t: TcpTransport, data) -> bytes:
    payload = t._codec.serialize(Message.create(qualifier="serve/event", data=data))
    return t._encode(payload, t._config.max_frame_length)


async def _collect_stream(t: TcpTransport, got: list):
    async for msg in t.listen():
        got.append(msg.data)


@pytest.mark.asyncio
async def test_slow_loris_evicted_by_idle_deadline():
    """A client trickling a frame header then going silent must be evicted
    by ``accept_idle_timeout_ms`` (and counted), not pin a handler until
    stop(); honest traffic on a fresh connection still flows after."""
    b = await TcpTransport.bind(
        TransportConfig(connect_timeout=1000, accept_idle_timeout_ms=100)
    )
    got: list = []
    task = asyncio.create_task(_collect_stream(b, got))
    try:
        reader, writer = await asyncio.open_connection(
            b.address.host, b.address.port
        )
        writer.write(b"\x00\x00")  # half a frame header, then silence
        await writer.drain()
        # The server must close us at the idle deadline: EOF on our reader.
        assert await asyncio.wait_for(reader.read(), timeout=2) == b""
        assert b.accept_idle_timeouts == 1
        writer.close()
        # The listener is unharmed: a fresh honest connection serves.
        _, w2 = await asyncio.open_connection(b.address.host, b.address.port)
        w2.write(_valid_frame(b, "after-loris"))
        await w2.drain()
        await asyncio.sleep(0.05)
        assert got == ["after-loris"]
        w2.close()
    finally:
        task.cancel()
        await b.stop()


@pytest.mark.asyncio
async def test_garbage_bytes_poison_only_their_connection():
    """Pure garbage (no framing at all) must cost the hostile connection,
    never the listener: the stream is dropped (counted when the bogus
    length header is over-limit) and valid traffic keeps flowing."""
    b = await bind()
    got: list = []
    task = asyncio.create_task(_collect_stream(b, got))
    try:
        _, wbad = await asyncio.open_connection(b.address.host, b.address.port)
        wbad.write(b"\xff\xff\xff\xff" + bytes(range(64)))  # 4 GiB "frame"
        await wbad.drain()
        await asyncio.sleep(0.05)
        assert b.frames_oversized == 1
        wbad.close()
        _, wok = await asyncio.open_connection(b.address.host, b.address.port)
        wok.write(_valid_frame(b, "still-serving"))
        await wok.drain()
        await asyncio.sleep(0.05)
        assert got == ["still-serving"]
        wok.close()
    finally:
        task.cancel()
        await b.stop()


@pytest.mark.asyncio
async def test_oversized_frame_then_valid_on_fresh_connection():
    """An over-limit frame poisons ITS stream (frames decoded ahead of the
    poison are still dispatched — the Netty-decode-loop contract) and the
    next connection starts clean."""
    b = await TcpTransport.bind(
        TransportConfig(connect_timeout=1000, max_frame_length=256)
    )
    got: list = []
    task = asyncio.create_task(_collect_stream(b, got))
    try:
        reader, writer = await asyncio.open_connection(
            b.address.host, b.address.port
        )
        # One valid frame, then an oversized header IN THE SAME WRITE: the
        # valid frame must still be dispatched before the stream dies.
        writer.write(
            _valid_frame(b, "before-poison") + (4096).to_bytes(4, "big") + b"\xff" * 8
        )
        await writer.drain()
        assert await asyncio.wait_for(reader.read(), timeout=2) == b""  # closed
        assert b.frames_oversized == 1
        writer.close()
        _, w2 = await asyncio.open_connection(b.address.host, b.address.port)
        w2.write(_valid_frame(b, "fresh-conn"))
        await w2.drain()
        await asyncio.sleep(0.05)
        assert got == ["before-poison", "fresh-conn"]
        w2.close()
    finally:
        task.cancel()
        await b.stop()


@pytest.mark.asyncio
async def test_undecodable_payload_counted_and_closed():
    """Well-framed but undecodable bytes: counted (``decode_failures``),
    the connection dropped, the listener unharmed."""
    b = await bind()
    got: list = []
    task = asyncio.create_task(_collect_stream(b, got))
    try:
        reader, writer = await asyncio.open_connection(
            b.address.host, b.address.port
        )
        writer.write(b._encode(b"\x80 not json", b._config.max_frame_length))
        await writer.drain()
        assert await asyncio.wait_for(reader.read(), timeout=2) == b""  # closed
        assert b.decode_failures == 1
        writer.close()
        _, w2 = await asyncio.open_connection(b.address.host, b.address.port)
        w2.write(_valid_frame(b, "ok"))
        await w2.drain()
        await asyncio.sleep(0.05)
        assert got == ["ok"]
        w2.close()
    finally:
        task.cancel()
        await b.stop()


@pytest.mark.asyncio
async def test_connect_churn_during_stop_drain():
    """Clients dialing (and dropping) connections WHILE stop() drains must
    neither crash the listener nor stall the drain past its grace."""
    b = await TcpTransport.bind(
        TransportConfig(connect_timeout=1000, stop_drain_ms=150)
    )
    got: list = []
    task = asyncio.create_task(_collect_stream(b, got))
    # Established connections with in-flight frames stop() must drain.
    writers = []
    for i in range(3):
        _, w = await asyncio.open_connection(b.address.host, b.address.port)
        w.write(_valid_frame(b, i))
        writers.append(w)
    await asyncio.sleep(0.05)
    stop_task = asyncio.create_task(b.stop())
    # Churn against the closing listener: dial, write, drop, repeat.
    for _ in range(5):
        try:
            _, w = await asyncio.open_connection(b.address.host, b.address.port)
            w.write(b"\x00")
            w.close()
        except OSError:
            pass  # listener already closed — the expected end state
        await asyncio.sleep(0.01)
    await asyncio.wait_for(stop_task, timeout=3)
    assert sorted(got[:3]) == [0, 1, 2]
    await asyncio.wait_for(task, timeout=2)  # streams completed
    for w in writers:
        w.close()


@pytest.mark.asyncio
async def test_accept_cap_sheds_connections():
    """Over ``max_accepted_connections`` the accept is closed immediately
    and counted — bounded handler memory under a connection flood."""
    b = await TcpTransport.bind(
        TransportConfig(connect_timeout=1000, max_accepted_connections=2)
    )
    got: list = []
    task = asyncio.create_task(_collect_stream(b, got))
    writers = []
    try:
        for _ in range(2):
            _, w = await asyncio.open_connection(b.address.host, b.address.port)
            w.write(_valid_frame(b, "kept"))
            await w.drain()
            writers.append(w)
        await asyncio.sleep(0.05)  # both handlers registered
        r3, w3 = await asyncio.open_connection(b.address.host, b.address.port)
        writers.append(w3)
        assert await asyncio.wait_for(r3.read(), timeout=2) == b""  # shed
        assert b.accept_shed == 1
        await asyncio.sleep(0.05)
        assert got == ["kept", "kept"]  # capped, not broken
    finally:
        for w in writers:
            w.close()
        task.cancel()
        await b.stop()


@pytest.mark.asyncio
async def test_pause_resume_reading_gates_delivery():
    """pause_reading() stops frame delivery (the batcher-full backpressure
    hook); resume_reading() delivers everything buffered meanwhile."""
    a, b = await bind(), await bind()
    got: list = []
    task = asyncio.create_task(_collect_stream(b, got))
    try:
        await a.send(
            b.address, Message.create(qualifier="x", data=0, sender=a.address)
        )
        await asyncio.sleep(0.05)
        assert got == [0]
        b.pause_reading()
        b.pause_reading()  # idempotent: one transition counted
        assert b.backpressure_pauses == 1
        await a.send(
            b.address, Message.create(qualifier="x", data=1, sender=a.address)
        )
        await asyncio.sleep(0.1)
        assert got == [0], "paused transport must not deliver"
        b.resume_reading()
        await asyncio.sleep(0.1)
        assert got == [0, 1], "resume must deliver the buffered frame"
    finally:
        task.cancel()
        await a.stop()
        await b.stop()


@pytest.mark.asyncio
async def test_dial_failure_book_bounded():
    """Regression (ISSUE 12): ``_dial_failures`` used to grow one entry per
    dead destination forever. The book is now bounded in size (stalest-first
    eviction) and age (TTL pruning), and a successful connect clears its
    entry."""
    from scalecube_cluster_tpu.transport import tcp as tcp_mod

    a = await bind()
    try:
        # Size bound: overfill via the accounting hook (no real dials).
        for i in range(tcp_mod._DIAL_FAILURES_MAX + 50):
            a._note_dial_failure(Address("10.255.0.1", 1 + i))
        assert len(a._dial_failures) <= tcp_mod._DIAL_FAILURES_MAX
        assert set(a._dial_failures) == set(a._dial_failure_ts)
        # Age bound: entries stamped before the TTL horizon are pruned by
        # the next failure note.
        stale = Address("10.255.0.2", 9)
        a._dial_failures[stale] = 3
        a._dial_failure_ts[stale] = -1e9  # long before any TTL horizon
        a._note_dial_failure(Address("10.255.0.3", 10))
        assert stale not in a._dial_failures
        assert stale not in a._dial_failure_ts
    finally:
        await a.stop()


@pytest.mark.asyncio
async def test_successful_connect_clears_failure_book_timestamps():
    """The success path must clear BOTH the count and the timestamp — a
    count cleared without its timestamp would leak the ts dict instead."""
    a, b = await bind(), await bind()
    try:
        a._dial_failures[b.address] = 2
        a._dial_failure_ts[b.address] = 0.0
        a._config = dataclasses.replace(a._config, reconnect_backoff_min_ms=1)
        await a.send(
            b.address, Message.create(qualifier="x", data=0, sender=a.address)
        )
        assert b.address not in a._dial_failures
        assert b.address not in a._dial_failure_ts
    finally:
        await a.stop()
        await b.stop()


@register_data_type("test/payload")
@dataclasses.dataclass(frozen=True)
class _Payload:
    name: str
    count: int
    nested: dict


def test_codec_roundtrip_registered_dataclass():
    codec = JsonMessageCodec()
    msg = Message.create(
        qualifier="q/x",
        data=_Payload("n", 7, {"k": [1, 2, {"d": None}]}),
        correlation_id="cid-9",
        sender=Address("10.0.0.1", 4801),
    )
    out = codec.deserialize(codec.serialize(msg))
    assert out.data == _Payload("n", 7, {"k": [1, 2, {"d": None}]})
    assert out.qualifier == "q/x" and out.correlation_id == "cid-9"
    assert out.sender == Address("10.0.0.1", 4801)


def test_codec_rejects_unregistered_type():
    class NotRegistered:
        pass

    codec = JsonMessageCodec()
    with pytest.raises(TypeError):
        codec.serialize(Message.create(qualifier="q", data=NotRegistered()))
