"""is_overrides truth table, pinned to MembershipRecordTest.java:34-109."""

import pytest

from scalecube_cluster_tpu import Address, Member, MemberStatus, MembershipRecord
from scalecube_cluster_tpu.cluster_api.membership_record import is_overrides

MEMBER = Member(id="m0", address=Address("127.0.0.1", 4801))


def rec(status: MemberStatus, incarnation: int = 0) -> MembershipRecord:
    return MembershipRecord(MEMBER, status, incarnation)


ALIVE, SUSPECT, DEAD = MemberStatus.ALIVE, MemberStatus.SUSPECT, MemberStatus.DEAD


def test_overrides_null_record():
    # Only ALIVE may introduce an unknown member (MembershipRecordTest:
    # r1Dead/r1Suspect do NOT override a null record).
    assert is_overrides(rec(ALIVE), None)
    assert not is_overrides(rec(SUSPECT), None)
    assert not is_overrides(rec(DEAD), None)


def test_dead_is_sticky():
    # An existing DEAD record is never overridden...
    for status in (ALIVE, SUSPECT, DEAD):
        for inc in (0, 1, 100):
            assert not is_overrides(rec(status, inc), rec(DEAD, 0))
    # ...and an incoming DEAD record overrides any non-dead record.
    for status in (ALIVE, SUSPECT):
        for inc in (0, 1, 100):
            assert is_overrides(rec(DEAD, 0), rec(status, inc))


@pytest.mark.parametrize("incoming", [ALIVE, SUSPECT])
@pytest.mark.parametrize("existing", [ALIVE, SUSPECT])
def test_higher_incarnation_wins(incoming, existing):
    assert is_overrides(rec(incoming, 1), rec(existing, 0))
    assert not is_overrides(rec(incoming, 0), rec(existing, 1))


def test_equal_incarnation_only_suspect_overrides_alive():
    assert is_overrides(rec(SUSPECT, 5), rec(ALIVE, 5))
    assert not is_overrides(rec(ALIVE, 5), rec(SUSPECT, 5))
    assert not is_overrides(rec(ALIVE, 5), rec(ALIVE, 5))
    assert not is_overrides(rec(SUSPECT, 5), rec(SUSPECT, 5))


def test_different_member_raises():
    other = MembershipRecord(
        Member(id="other", address=Address("127.0.0.1", 4802)), MemberStatus.ALIVE
    )
    with pytest.raises(ValueError):
        is_overrides(rec(ALIVE), other)
