"""Headline benchmark: member-gossip-rounds per second on one chip.

Simulates a dense SWIM cluster (sim/) at the largest member count that fits
single-chip HBM dense, under LAN protocol ratios with 5% packet loss — the
BASELINE.json "1k-member SWIM sim, 5% packet loss + suspicion" config scaled
up. One tick advances every member one gossip round (plus the FD/SYNC work on
their cadence), so throughput = n_members × ticks/sec, measured against the
driver's north-star 1M member-gossip-rounds/sec (BASELINE.json north_star).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

from __future__ import annotations

import json
import time

import jax

BASELINE_MEMBER_ROUNDS_PER_SEC = 1_000_000.0


def bench(n_members: int = 10240, chunk: int = 40, reps: int = 4) -> dict:
    from scalecube_cluster_tpu.sim import FaultPlan, SimParams, init_full_view, run_ticks
    from scalecube_cluster_tpu.sim.state import seeds_mask

    params = SimParams.from_cluster_config(n_members)
    state = init_full_view(n_members)
    plan = FaultPlan.clean(n_members).with_loss(5.0)
    seeds = seeds_mask(n_members, [0, 1])

    # Warmup: compile + reach protocol steady state. NOTE: timings sync via a
    # host fetch of the tick counter — jax.block_until_ready can report ready
    # prematurely over this box's tunneled-TPU transport.
    state, traces = run_ticks(params, state, plan, seeds, chunk, collect=False)
    int(state.tick)

    t0 = time.perf_counter()
    for _ in range(reps):
        state, traces = run_ticks(params, state, plan, seeds, chunk, collect=False)
        int(state.tick)
    dt = time.perf_counter() - t0

    value = n_members * (reps * chunk / dt)
    return {
        "metric": f"member_gossip_rounds_per_sec_n{n_members}",
        "value": round(value, 1),
        "unit": "member·rounds/s",
        "vs_baseline": round(value / BASELINE_MEMBER_ROUNDS_PER_SEC, 3),
    }


if __name__ == "__main__":
    print(json.dumps(bench()))
