"""Headline benchmark: member-gossip-rounds per second on one chip.

Simulates a SWIM cluster under LAN protocol ratios with 5% packet loss and
one genuinely-failed member — the BASELINE.json "1k-member SWIM sim, 5%
packet loss + suspicion" config scaled up. One tick advances every member
one gossip round (plus the FD/SYNC work on their cadence), so throughput =
n_members × ticks/sec, measured against the driver's north-star 1M
member-gossip-rounds/sec (BASELINE.json north_star).

Two engines climb the ladder largest-first:

- ``sparse`` — the compact-rumor working-set engine (sim/sparse.py),
  O(N·S) per tick: the scale path (SURVEY.md §7 hard part 4). Runs with
  host-boundary slot frees (in_scan_writeback=False) and a compact uniform
  fault plan so a single chip holds ~49k members.
- ``dense`` — the full [N, N] engine (sim/tick.py) with the fused Pallas
  tick-core kernel (ops/pallas_tick.py), the validation-scale engine.

Hardened per VERDICT.md round-1 item 1: this script ALWAYS prints exactly
one JSON line on stdout, no matter what the TPU tunnel does.

- A tiny probe op with a hard deadline runs first, retried until the total
  budget is spent; if the backend never comes up, the JSON line carries an
  ``"error"`` field plus the last committed self-measured number and commit
  hash (``PERF_SELF.json``), so an outage round still reports evidence.
- Each measured config runs in a subprocess with its own deadline, so a
  mid-dispatch hang (the round-1 failure mode: BENCH_r01.json rc=1, later
  re-runs hanging >4 min) is converted into a fallback down the ladder.
- Timing syncs via a one-element host fetch off a LARGE output buffer —
  jax.block_until_ready and small-output fetches can both report ready
  prematurely over this box's tunneled-TPU transport (each output buffer's
  ready event completes independently).

Usage: ``python bench.py`` (driver mode — one JSON line),
``python bench.py --child <engine> <n>`` (internal single-config worker),
``python bench.py --telemetry [out.jsonl] [n]`` (flight-recorder run: counter
totals + detection-latency histograms as schema-versioned JSONL + Prometheus),
``python bench.py --ensemble <B> [n]`` (vmapped multi-universe rung,
sim/ensemble.py: B universes stepped in one compiled call; the reported
aggregate is universes × member·rounds/s), ``python bench.py --rapid
[n]`` (the Rapid consistent-membership engine rung, sim/rapid.py — the
measured price of strong consistency next to the SWIM numbers), or
``python bench.py --shard-map <d> [n] [--pallas]`` (the explicit-SPMD
engine rung, parallel/spmd.py: the sparse tick as a shard_map program
over d member shards with bucketed cross-shard exchange; ``--pallas``
swaps each shard's merge/decay core for the fused Pallas kernel, same
collective geometry. Rows are stamped with the shard count, the resolved
bucket capacity and the exchange-round count, and both the backend probe
attempt and the result row land in artifacts/bench_history.jsonl. On a
CPU-only box set JAX_PLATFORMS=cpu and the rung forces d virtual host
devices itself), ``python bench.py --persistent-ksweep [n] [k_max]``
(the persistent multi-tick kernel swept over launch depth k on one
traced executable — one row per k with ns_per_member and a
zero_recompile verdict pinned via jit_cache_size), or ``python bench.py
--serve [n]`` (the streaming serving-bridge rung, serve/: a synthetic
event stream replayed through the double-buffered launch pipeline; the
``kind="serve"`` session row — events/s, member·rounds/s, batch-latency
percentiles — plus the probe attempt land in bench_history.jsonl), or
``python bench.py --load [producers] [n]`` (the wire-rate rung,
serve/load.py: a seeded fleet of honest + adversarial loopback-TCP
producers with churn drives one live session; the ``kind="load"`` row —
events/s, backpressure pauses, rejections, conservation verdicts — plus
the probe attempt land in bench_history.jsonl), or ``python bench.py --grow [n0] [tiers]`` (the
elastic-membership rung, serve/bridge.py + sim/checkpoint.py: one serving
session grows from n0 live members to a full ``2*n0 * 2**tiers`` through
``tiers`` auto-promotions under wire-form joins; the ``kind="grow"`` row —
joins/s admission rate, per-promotion wall-time, certified ``dropped: 0``
— plus the probe attempt land in bench_history.jsonl), or ``python bench.py
--tracer-overhead [n]`` (the flight-recorder cost rung: the same churny
sparse trajectory run tracer-off and tracer-on; the ``kind="bench_tracer"``
row carries the on/off wall-time ratio, tracer-on ns_per_member, and the
events-recorded/overflow accounting, and both the probe attempt and the
row land in bench_history.jsonl), or ``python bench.py
--geo [n]`` (the geo-distributed rung, sim/topology.py: the dense engine
under a 2-zone 400 ms WAN brownout schedule; the ``kind="bench_geo"``
row reports member·rounds/s, ns_per_member and the flat-world overhead
ratio, and both the probe attempt and the row land in
bench_history.jsonl), or ``python bench.py --fleet [B] [n]`` (the
multi-tenant fleet control-plane rung, serve/fleet.py: B tenant universes
multiplexed onto one vmapped serving executable, each replaying the
--serve rung's synthetic gossip stream; the ``kind="fleet"`` row — per-
tenant ingest→verdict p50/p95/p99, aggregate tenant·member·rounds/s, and
the fleet-of-B vs B-solo-sessions wall ratio — plus the probe attempt
land in bench_history.jsonl).
"""

from __future__ import annotations

import json
import math
import os
import subprocess
import sys
import time

BASELINE_MEMBER_ROUNDS_PER_SEC = 1_000_000.0

#: Observed peak working set of THE bench trajectory (one kill, 5% loss,
#: 240 ticks) at the 32768 reference rung — slot_overflow 0 at S=512 and
#: S=1024 over the full run (artifacts/s_overflow_check.json; seeded and
#: backend-independent, so the CPU check binds the TPU run).
_BENCH_PEAK_SLOTS_32768 = 455


def _rung_slot_budget(n: int) -> int:
    """Rule-sized sparse rung S (round-6 satellite): scale the observed
    peak working set linearly in n (FD/churn arrivals and the sync window
    are both ~rate × n), add a 12.5% burst margin, and round up to the
    kernel's 128-lane tile. Yields the proven 512 at the 32768 reference
    and ~768 at 49152 (whose first overflow at a hardcoded 512 is noted in
    PERF.md) instead of one hardcoded width for every n.
    """
    peak = _BENCH_PEAK_SLOTS_32768 * n / 32768.0
    return max(128 * math.ceil(peak * 1.125 / 128.0), 256)
#: Best-value-first ladder of (engine, n_members); first one that lands
#: wins. ``sparse-pallas`` (the fused [N, S] kernel core) leads: if it
#: lowers on the chip it beats the XLA chain; if it fails the child dies
#: and the ladder falls through to the proven plain-sparse rung.
#: 32768 is the VALUE-optimal rung, not a ceiling any more: the round-2
#: >8-min compile degeneration at 40960/49152 was in the XLA tick chain
#: — with the fused kernel replacing it, both compile in ~15 s and RUN on
#: one chip (tools/compile_wall.py + tools/sparse_times.py, round 3), but
#: per-tick cost grows super-linearly (23.4 ms @32768 → 35.3 ms @40960),
#: so member·rounds/s peaks at 32768. ``dense-xla`` rungs keep a
#: measurement landing even if the fused Pallas kernel ever fails to
#: lower on the target chip.
#: 40960/49152 are deliberately NOT rungs: a rung below the 32768 pair is
#: only reached after sparse-pallas already failed at 32768 — it would
#: fail identically at larger n and just burn child budget.
#: Rung = (engine, n, slot_budget or None=for_n default). Round-4 S
#: right-sizing (VERDICT r3 weak #2): the bench trajectory's working set
#: peaks at 455 slots (artifacts/s_overflow_check.json — slot_overflow 0
#: at S=512 AND S=1024 over the full 240 ticks; the trajectory is seeded
#: and backend-independent, so the CPU check binds the TPU run), while
#: kernel cost is ~linear in S — S=512 sheds ~75% of the slab sweep vs
#: the round-3 S=2048 headline config. The S=2048 rungs stay as proven
#: fallbacks.
LADDER = (
    ("sparse-pallas", 32768, _rung_slot_budget(32768)),
    ("sparse-pallas", 32768, 2048),
    ("sparse", 32768, _rung_slot_budget(32768)),
    ("sparse", 32768, 2048),
    ("sparse", 16384, _rung_slot_budget(16384)),
    ("dense", 10240, None),
    ("dense-xla", 10240, None),
    ("dense", 4096, None),
    ("dense-xla", 4096, None),
    ("dense-xla", 1024, None),
)
#: TPU probe budget, env-tunable (round-6 satellite): outage rounds burned
#: 8 × 120 s probing before the 0.0 row (BENCH_r05) — operators who know
#: the tunnel is down can shrink it, soak runs can raise it.
PROBE_DEADLINE_S = int(os.environ.get("SC_BENCH_PROBE_BUDGET_S", "120"))
CHILD_DEADLINE_S = 420
#: Hard budget on total wall time before the JSON line must be out — stops
#: starting new children once exceeded, so a wedged backend can't push the
#: guaranteed output past the driver's patience (probe + first child worst
#: case still fits well under it).
TOTAL_BUDGET_S = 1200


def _ns_per_member(value: float) -> float | None:
    """Wall nanoseconds per member·round (1e9 / member·rounds/s) — the
    flat-scaling lens (round-7 satellite): a rung family scales linearly
    exactly while this column stays flat as n grows, so scaling knees read
    straight off bench_history.jsonl without dividing throughput columns
    by hand. ``None`` when the rung never produced a measurement."""
    return round(1e9 / value, 3) if value > 0 else None


def _measure_dense(
    n_members: int, pallas: bool = True, chunk: int = 40, reps: int = 4
) -> float:
    from scalecube_cluster_tpu.sim import FaultPlan, SimParams, init_full_view, run_ticks
    from scalecube_cluster_tpu.sim.state import kill, seeds_mask
    import dataclasses

    params = dataclasses.replace(
        SimParams.from_cluster_config(n_members), pallas_delivery=pallas
    )
    state = kill(init_full_view(n_members), 7)
    plan = FaultPlan.uniform(loss_percent=5.0)
    seeds = seeds_mask(n_members, [0, 1])

    # Warmup: compile + reach protocol steady state. The element fetch off
    # the LARGE view buffer is the host sync: one element waits for that
    # whole buffer's ready event, and intermediate chunks are serialized by
    # the feed-back data dependency (see module docstring).
    state, _ = run_ticks(params, state, plan, seeds, chunk, collect=False)
    int(state.view[0, 0])

    t0 = time.perf_counter()
    for _ in range(reps):
        state, _ = run_ticks(params, state, plan, seeds, chunk, collect=False)
        int(state.view[0, 0])
    dt = time.perf_counter() - t0
    return n_members * (reps * chunk / dt)


def _measure_sparse(
    n_members: int,
    chunk: int = 48,
    reps: int = 4,
    pallas: bool = False,
    slot_budget: int | None = None,
) -> float:
    from scalecube_cluster_tpu.sim.faults import FaultPlan
    from scalecube_cluster_tpu.sim.sparse import (
        SparseParams,
        init_sparse_full_view,
        kill_sparse,
        run_sparse_chunked,
    )

    from scalecube_cluster_tpu.obs.profiling import trace_scope

    kw = {"slot_budget": slot_budget} if slot_budget else {}
    params = SparseParams.for_n(
        n_members, in_scan_writeback=False, pallas_core=pallas, **kw
    )
    state = kill_sparse(
        init_sparse_full_view(n_members, params.slot_budget), 7
    )
    plan = FaultPlan.uniform(loss_percent=5.0)

    state, _ = run_sparse_chunked(params, state, plan, chunk, chunk, collect=False)
    int(state.view_T[0, 0])

    t0 = time.perf_counter()
    for rep in range(reps):
        # Named scope so a jax.profiler capture attributes each chunk
        # dispatch (no-op cost when no trace is being collected).
        with trace_scope(f"bench/sparse_chunk_rep{rep}"):
            state, _ = run_sparse_chunked(
                params, state, plan, chunk, chunk, collect=False
            )
            int(state.view_T[0, 0])
    dt = time.perf_counter() - t0
    return n_members * (reps * chunk / dt)


def _measure_ensemble(
    b_count: int, n_members: int = 1024, chunk: int = 40, reps: int = 4
) -> dict:
    """The ``--ensemble B`` rung: B dense universes under independent
    uniform-5%-loss plans stepped together by sim/ensemble.py — ONE compiled
    call per timing rep, ``collect=False``. The aggregate metric is
    universes × member·rounds/s (B · n · ticks / dt): what one chip
    sustains across a whole population, the sweep-throughput number PERF.md
    accounts for. Uses the XLA tick core — vmap batches it directly."""
    import dataclasses

    from scalecube_cluster_tpu.sim import FaultPlan, SimParams
    from scalecube_cluster_tpu.sim.ensemble import (
        init_ensemble_dense,
        run_ensemble_ticks,
        stack_universes,
    )
    from scalecube_cluster_tpu.sim.state import seeds_mask

    params = dataclasses.replace(
        SimParams.from_cluster_config(n_members), pallas_delivery=False
    )
    states = init_ensemble_dense(
        n_members, range(b_count), user_gossip_slots=params.user_gossip_slots
    )
    plans = stack_universes(
        FaultPlan.uniform(loss_percent=5.0) for _ in range(b_count)
    )
    seeds = seeds_mask(n_members, [0, 1])

    # Warmup (compile + steady state); the element fetch off the large
    # stacked view buffer is the host sync, as in the single-run rungs.
    states, _ = run_ensemble_ticks(params, states, plans, seeds, chunk, collect=False)
    int(states.view[0, 0, 0])

    t0 = time.perf_counter()
    for _ in range(reps):
        states, _ = run_ensemble_ticks(
            params, states, plans, seeds, chunk, collect=False
        )
        int(states.view[0, 0, 0])
    dt = time.perf_counter() - t0
    value = b_count * n_members * (reps * chunk / dt)
    return {
        "metric": "ensemble_member_gossip_rounds_per_sec",
        "value": round(value, 1),
        "unit": "universes·member·rounds/s",
        "per_universe": round(value / b_count, 1),
        "ns_per_member": _ns_per_member(value),
        "vs_baseline": round(value / BASELINE_MEMBER_ROUNDS_PER_SEC, 3),
        "n_members": n_members,
        "universes": b_count,
        "engine": "dense-ensemble",
    }


def _measure_shard_map(
    d: int, n_members: int = 32768, chunk: int = 48, reps: int = 4,
    pallas: bool = False,
) -> dict:
    """The ``--shard-map d [n] [--pallas]`` rung: the explicit-SPMD sparse
    engine (parallel/spmd.py) over a d-shard ``members`` mesh, measured
    exactly like the sparse rungs (warmup + compile, then reps × chunk
    scanned ticks synced by an element fetch off the large view_T buffer).
    ``pallas=True`` (round-7 tentpole arm) swaps each shard's merge/decay
    core for the fused Pallas kernel — the three cross-shard collectives
    stay outside the kernel, identical geometry — under the engine tag
    ``sparse-shard-map-pallas``, so the kernel-vs-XLA-core delta at the
    same shard count reads as two adjacent rows. The row carries the
    exchange geometry next to the throughput number — shard count,
    resolved per-(channel, destination) bucket capacity in sender groups,
    exchange rounds per tick, and the analytic exchange payload in
    bytes/tick — so GSPMD-vs-explicit-SPMD comparisons in PERF.md read
    straight off bench_history.jsonl rows."""
    import jax

    from scalecube_cluster_tpu.parallel.mesh import make_mesh
    from scalecube_cluster_tpu.parallel.spmd import (
        ShardConfig,
        _bucket_cap,
        exchange_payload_bytes_per_tick,
        exchange_rounds_per_tick,
        run_sparse_ticks_spmd,
    )
    from scalecube_cluster_tpu.sim.faults import FaultPlan
    from scalecube_cluster_tpu.sim.sparse import (
        SparseParams,
        init_sparse_full_view,
        kill_sparse,
    )

    if len(jax.devices()) < d:
        raise RuntimeError(
            f"--shard-map {d} needs {d} devices, found {len(jax.devices())}"
        )
    # The explicit engine keeps slot frees IN the scan (the free decision
    # is one replicated psum, no host boundary needed) — unlike the
    # GSPMD sparse rung, which runs chunked with host-boundary frees.
    params = SparseParams.for_n(
        n_members,
        in_scan_writeback=True,
        slot_budget=_rung_slot_budget(n_members),
        pallas_core=pallas,
    )
    cfg = ShardConfig(d=d)
    mesh = make_mesh(jax.devices()[:d])
    state = kill_sparse(init_sparse_full_view(n_members, params.slot_budget), 7)
    plan = FaultPlan.uniform(loss_percent=5.0)

    state, _ = run_sparse_ticks_spmd(
        params, cfg, mesh, state, plan, chunk, collect=False
    )
    int(state.view_T[0, 0])

    t0 = time.perf_counter()
    for rep in range(reps):
        state, _ = run_sparse_ticks_spmd(
            params, cfg, mesh, state, plan, chunk, collect=False
        )
        int(state.view_T[0, 0])
    dt = time.perf_counter() - t0
    value = n_members * (reps * chunk / dt)
    return {
        "metric": "member_gossip_rounds_per_sec",
        "value": round(value, 1),
        "unit": "member·rounds/s",
        "vs_baseline": round(value / BASELINE_MEMBER_ROUNDS_PER_SEC, 3),
        "ns_per_member": _ns_per_member(value),
        "n_members": n_members,
        "engine": "sparse-shard-map-pallas" if pallas else "sparse-shard-map",
        "slot_budget": params.slot_budget,
        "shards": d,
        "bucket_groups": _bucket_cap(params, cfg),
        "exchange_rounds": exchange_rounds_per_tick(),
        # Priced per shard per tick by the same analytic model tpulint S2
        # cross-checks against the traced gossip buffer, so this column
        # can't silently drift from the engine.
        "exchange_bytes_per_tick": exchange_payload_bytes_per_tick(
            params, cfg
        )["total_bytes"],
    }


def _measure_persistent_ksweep(
    n_members: int = 4096,
    k_max: int = 8,
    reps: int = 4,
    slot_budget: int | None = None,
) -> list[dict]:
    """The ``--persistent-ksweep [n] [k_max]`` rung family: the persistent
    multi-tick kernel (ops/pallas_sparse.py::run_sparse_core_persistent)
    swept over launch depth k on ONE traced executable — k rides a scalar
    operand, so every 1 <= k <= k_max reuses the k_max-sized grid. One row
    per k, same member·rounds/s metric as the tick rungs plus
    ``ns_per_member``, so how per-launch overhead (dispatch + the first
    slab DMA fill) amortizes with depth reads as a row family in
    bench_history.jsonl. Every row carries ``zero_recompile`` pinned via
    jit_cache_size: a silently re-specializing executable fails loudly in
    the history instead of flattering the sweep. Operands are the same
    seeded realistic set the parity tests use (negative UNKNOWNs, partial
    slot table, dead rows) — this rung prices the kernel, not a protocol
    trajectory."""
    import jax.numpy as jnp
    import numpy as np

    from scalecube_cluster_tpu.ops.pallas_sparse import run_sparse_core_persistent
    from scalecube_cluster_tpu.utils.jaxcache import jit_cache_size

    s = slot_budget or _rung_slot_budget(n_members)
    f = 3
    nb = n_members // 32
    rng = np.random.default_rng(0)
    slab = jnp.asarray(rng.integers(-1, 1 << 20, (n_members, s)), jnp.int32)
    age = jnp.asarray(rng.integers(0, 120, (n_members, s)), jnp.int8)
    susp = jnp.asarray(rng.integers(0, 21, (n_members, s)), jnp.int16)
    subj = np.full(s, -1, np.int32)
    k_active = min(n_members, s // 2)
    subj[:k_active] = rng.choice(n_members, size=k_active, replace=False)
    rng.shuffle(subj)
    slot_subj = jnp.asarray(subj)
    ginv = jnp.asarray(rng.integers(0, nb, (k_max, f, nb)), jnp.int32)
    rots = jnp.asarray(rng.integers(0, 32, (k_max, f, nb)), jnp.int32)
    edge_ok = jnp.asarray(rng.random((k_max, f, n_members)) < 0.8)
    alive = jnp.asarray(rng.random(n_members) < 0.9)
    kw = dict(
        spread=6, susp_ticks=20, age_stale=120, sweep=6, k_max=k_max,
        fold=frozenset({"countdown", "wb_mask", "view_rows"}),
    )

    def launch(k: int):
        return run_sparse_core_persistent(
            slab, age, susp, slot_subj, ginv, rots, edge_ok, alive, k, **kw
        )

    before = jit_cache_size(run_sparse_core_persistent)
    # One warmup launch at full depth pays the single compile; the element
    # fetch off the large slab output is the host sync (module docstring).
    int(launch(k_max)[0][0, 0])
    rows = []
    for k in range(1, k_max + 1):
        t0 = time.perf_counter()
        for _ in range(reps):
            int(launch(k)[0][0, 0])
        dt = time.perf_counter() - t0
        value = n_members * (reps * k / dt)
        rows.append({
            "metric": "member_gossip_rounds_per_sec",
            "value": round(value, 1),
            "unit": "member·rounds/s",
            "vs_baseline": round(value / BASELINE_MEMBER_ROUNDS_PER_SEC, 3),
            "ns_per_member": _ns_per_member(value),
            "n_members": n_members,
            "engine": "sparse-persistent-kernel",
            "slot_budget": s,
            "k": k,
            "k_max": k_max,
            "launches": reps,
            "zero_recompile": jit_cache_size(run_sparse_core_persistent)
            == before + 1,
        })
    return rows


def _measure_rapid(n_members: int = 1024, chunk: int = 40, reps: int = 4) -> dict:
    """The ``--rapid [n]`` rung: the consistent-membership engine
    (sim/rapid.py) under the bench's standard uniform-5%-loss plan,
    ``collect=False``, timed exactly like the SWIM rungs (warmup + compile,
    then reps × chunk scanned ticks synced by an element fetch off the
    large [N, N] member-mask buffer). Same member·rounds/s metric,
    schema-stamped — so PERF.md can put the price of strong consistency
    (O(N²·k) alarm/vote broadcasts per tick) next to the SWIM numbers
    rather than leaving it a qualitative claim."""
    from scalecube_cluster_tpu.sim.faults import FaultPlan
    from scalecube_cluster_tpu.sim.rapid import (
        RapidParams,
        init_rapid_full_view,
        run_rapid_ticks,
    )

    params = RapidParams(n=n_members)
    state = init_rapid_full_view(params)
    plan = FaultPlan.uniform(loss_percent=5.0)

    state, _ = run_rapid_ticks(params, state, plan, chunk, collect=False)
    bool(state.member_mask[0, 0])

    t0 = time.perf_counter()
    for _ in range(reps):
        state, _ = run_rapid_ticks(params, state, plan, chunk, collect=False)
        bool(state.member_mask[0, 0])
    dt = time.perf_counter() - t0
    value = n_members * (reps * chunk / dt)
    return {
        "metric": "member_gossip_rounds_per_sec",
        "value": round(value, 1),
        "unit": "member·rounds/s",
        "vs_baseline": round(value / BASELINE_MEMBER_ROUNDS_PER_SEC, 3),
        "ns_per_member": _ns_per_member(value),
        "n_members": n_members,
        "engine": "rapid",
        "k_observers": params.k,
    }


def _measure_geo(n_members: int = 1024, chunk: int = 40, reps: int = 4) -> dict:
    """The ``--geo [n]`` rung: the dense engine under a 2-zone WAN brownout
    (sim/topology.py) — 400 ms cross-zone latency inflation composed over
    the bench's standard uniform-5%-loss plan via a FaultSchedule whose
    segment carries the LinkWorld. Timed exactly like the SWIM rungs
    (collect=False, warmup + reps × chunk, large-buffer element sync). The
    row reports both the geo throughput and its flat-world twin (same
    schedule pytree shape, ``link_world=None``) so the per-edge zone-gather
    overhead — two O(1) gathers per matrix per tick — reads as a ratio
    straight off bench_history.jsonl (PERF.md geo note)."""
    from scalecube_cluster_tpu.sim import (
        FaultPlan,
        ScheduleBuilder,
        SimParams,
        init_full_view,
        run_ticks,
    )
    from scalecube_cluster_tpu.sim.state import seeds_mask
    from scalecube_cluster_tpu.sim.topology import LinkWorld

    params = SimParams.from_cluster_config(n_members)
    seeds = seeds_mask(n_members, [0, 1])
    world = LinkWorld.even_zones(n_members, 2).with_zone_latency(0, 1, 400.0)

    def run(link_world):
        sched = (
            ScheduleBuilder(n_members)
            .add_segment(
                0, FaultPlan.uniform(loss_percent=5.0), link_world=link_world
            )
            .build()
        )
        state = init_full_view(n_members)
        state, _ = run_ticks(params, state, sched, seeds, chunk, collect=False)
        int(state.view[0, 0])
        t0 = time.perf_counter()
        for _ in range(reps):
            state, _ = run_ticks(
                params, state, sched, seeds, chunk, collect=False
            )
            int(state.view[0, 0])
        dt = time.perf_counter() - t0
        return n_members * (reps * chunk / dt)

    flat_value = run(None)
    value = run(world)
    return {
        "metric": "member_gossip_rounds_per_sec",
        "value": round(value, 1),
        "unit": "member·rounds/s",
        "vs_baseline": round(value / BASELINE_MEMBER_ROUNDS_PER_SEC, 3),
        "ns_per_member": _ns_per_member(value),
        "n_members": n_members,
        "engine": "dense-geo",
        "n_zones": 2,
        "brownout_latency_ms": 400.0,
        "flat_value": round(flat_value, 1),
        "geo_overhead": round(flat_value / value, 4) if value > 0 else None,
    }


def _measure_serve(
    n_members: int = 4096,
    batch_ticks: int = 32,
    capacity: int = 8,
    n_batches: int = 8,
) -> dict:
    """The ``--serve [n]`` rung: the streaming serving bridge (serve/)
    replaying a synthetic user-gossip event stream through the double-
    buffered launch pipeline, ``collect=False``, under the bench's standard
    one-kill + 5%-loss trajectory. The row is the bridge's own
    ``kind="serve"`` session summary — events/s ingested-to-verdict,
    member·rounds/s through the serving path, and per-launch batch-latency
    percentiles (obs/latency.py) — so the serving overhead reads directly
    against the offline engine rungs in bench_history.jsonl. A one-batch
    warmup session on a throwaway state pays the (params, k, C) compile so
    the timed session measures steady-state serving, which is what the
    executable-reuse contract promises."""
    from scalecube_cluster_tpu.serve import EV_GOSSIP, ServeBridge, ServeEvent
    from scalecube_cluster_tpu.sim.faults import FaultPlan
    from scalecube_cluster_tpu.sim.sparse import (
        SparseParams,
        init_sparse_full_view,
        kill_sparse,
    )

    params = SparseParams.for_n(
        n_members, slot_budget=_rung_slot_budget(n_members)
    )
    plan = FaultPlan.uniform(loss_percent=5.0)

    warm = ServeBridge(
        params,
        init_sparse_full_view(n_members, params.slot_budget),
        plan=plan,
        batch_ticks=batch_ticks,
        capacity=capacity,
        collect=False,
    )
    warm.run_replay([], batch_ticks)

    state = kill_sparse(init_sparse_full_view(n_members, params.slot_budget), 7)
    bridge = ServeBridge(
        params,
        state,
        plan=plan,
        batch_ticks=batch_ticks,
        capacity=capacity,
        collect=False,
    )
    g_slots = bridge.batcher.g_slots
    total_ticks = batch_ticks * n_batches
    per_tick = max(capacity // 2, 1)
    events = [
        ServeEvent(
            EV_GOSSIP,
            (t * per_tick + j) % n_members,
            arg=(t + j) % g_slots,
            tick=t,
        )
        for t in range(1, total_ticks + 1)
        for j in range(per_tick)
    ]
    bridge.run_replay(events, total_ticks)
    return bridge.close()


def _measure_fleet(
    fleet_size: int = 4,
    n_members: int = 1024,
    batch_ticks: int = 16,
    capacity: int = 8,
    n_rounds: int = 8,
) -> dict:
    """The ``--fleet B [n]`` rung: B tenant universes multiplexed onto one
    vmapped serving executable (serve/fleet.py), each tenant replaying the
    same synthetic user-gossip stream the ``--serve`` rung uses,
    ``collect=False``. The row is the FleetBridge's own ``kind="fleet"``
    session summary — per-tenant ingest→verdict p50/p95/p99 and the
    aggregate tenant·member·rounds/s — augmented with the fleet-of-B vs
    B-solo-sessions wall ratio (one solo bridge timed over the same
    per-tenant trace, scaled by B): the multiplexing dividend PERF.md's
    "Fleet accounting" note reads directly off bench_history.jsonl."""
    from scalecube_cluster_tpu.serve import EV_GOSSIP, FleetBridge, ServeBridge, ServeEvent
    from scalecube_cluster_tpu.sim.faults import FaultPlan
    from scalecube_cluster_tpu.sim.sparse import SparseParams, init_sparse_full_view

    params = SparseParams.for_n(
        n_members, slot_budget=_rung_slot_budget(n_members)
    )
    plan = FaultPlan.uniform(loss_percent=5.0)
    total_ticks = batch_ticks * n_rounds
    per_tick = max(capacity // 2, 1)

    def tenant_stream(tenant: int):
        return [
            ServeEvent(
                EV_GOSSIP,
                (t * per_tick + j) % n_members,
                arg=(t + j) % 4,
                tick=t,
                tenant=tenant,
            )
            for t in range(1, total_ticks + 1)
            for j in range(per_tick)
        ]

    # Warm-up fleet session on throwaway states: pays the (params, B, k, C)
    # compile so the timed session measures steady-state serving.
    warm = FleetBridge(
        params, engine="sparse", fleet_size=fleet_size,
        batch_ticks=batch_ticks, capacity=capacity, plan=plan, collect=False,
    )
    warm.run_replay([], batch_ticks)

    fleet = FleetBridge(
        params, engine="sparse", fleet_size=fleet_size,
        batch_ticks=batch_ticks, capacity=capacity, plan=plan, collect=False,
    )
    events = [ev for b in range(fleet_size) for ev in tenant_stream(b)]
    t0 = time.perf_counter()
    fleet.run_replay(events, total_ticks)
    fleet_wall_s = time.perf_counter() - t0
    row = fleet.close()

    # Solo baseline: ONE tenant's stream through one solo session (its own
    # executable, already warm from the fleet warmup? no — solo entry
    # differs, pay its compile on a throwaway first).
    solo_warm = ServeBridge(
        params, init_sparse_full_view(n_members, params.slot_budget),
        plan=plan, batch_ticks=batch_ticks, capacity=capacity, collect=False,
    )
    solo_warm.run_replay([], batch_ticks)
    solo = ServeBridge(
        params, init_sparse_full_view(n_members, params.slot_budget),
        plan=plan, batch_ticks=batch_ticks, capacity=capacity, collect=False,
    )
    t0 = time.perf_counter()
    solo.run_replay(
        [ServeEvent(ev.kind, ev.node, arg=ev.arg, tick=ev.tick)
         for ev in tenant_stream(0)],
        total_ticks,
    )
    solo_wall_s = time.perf_counter() - t0
    solo.close()
    row["fleet_wall_s"] = round(fleet_wall_s, 4)
    row["solo_wall_s"] = round(solo_wall_s, 4)
    # > 1.0 means the fleet-of-B beat B sequential solo sessions.
    row["fleet_vs_solo_ratio"] = round(
        (fleet_size * solo_wall_s) / max(fleet_wall_s, 1e-9), 3
    )
    row["n_members"] = n_members
    return row


def _measure_grow(n0: int = 64, tiers: int = 2, burst: int = 24) -> dict:
    """The ``--grow [n0] [tiers]`` rung: one elastic serving session grows
    from ``n0`` live members (in a ``2*n0`` allocation, the first tier of
    the doubling ladder) to a full ``2*n0 * 2**tiers`` members through
    ``tiers`` checkpoint-based geometry promotions (serve/bridge.py
    ``auto_promote``) — the defaults are the certified 64 -> 512 session of
    tests/test_elastic.py as a priced rung. Joins arrive in wire form (node
    omitted — the bridge's admission allocator assigns capacity rows). The
    row prices the two costs elasticity adds to serving: steady-state
    admission (joins/s ingested-to-activated, launches riding the elastic
    executable) and the promotion wall-time itself (drain + pack_cold
    checkpoint round-trip + re-init at the doubled tier + parked-join
    replay + recompile at the new geometry, from the per-promotion
    ``wall_ms`` stamps). The admission conservation ledger is asserted at
    the end — ``dropped`` in the row is a certified 0, not an observation
    — so a growth session that sheds or strands a join fails the bench
    instead of flattering it."""
    from scalecube_cluster_tpu.serve import ServeBridge
    from scalecube_cluster_tpu.serve.ingest import event_from_obj
    from scalecube_cluster_tpu.sim.faults import FaultPlan
    from scalecube_cluster_tpu.sim.sparse import (
        SparseParams,
        init_sparse_full_view,
    )

    n_alloc0 = 2 * n0
    n_top = n_alloc0 * (2**tiers)
    params = SparseParams.for_n(n_alloc0, slot_budget=_rung_slot_budget(n_top))
    state = init_sparse_full_view(n0, params.slot_budget, n_alloc=n_alloc0)
    bridge = ServeBridge(
        params, state, plan=FaultPlan.uniform(), batch_ticks=8,
        capacity=max(burst, 8), collect=False, auto_promote=True,
    )
    n_joins = n_top - n0
    t0 = time.perf_counter()
    sent = 0
    while sent < n_joins or bridge.batcher.deferred_joins:
        for _ in range(min(burst, n_joins - sent)):
            bridge.push(event_from_obj({"kind": "join"}))
        sent += min(burst, n_joins - sent)
        bridge.step_batch()
    dt = time.perf_counter() - t0
    led = bridge.batcher.assert_join_conservation()
    assert led["placed"] == n_joins and led["shed"] == 0, led
    promo_ms = [
        r["wall_ms"] for r in bridge.rows if r.get("kind") == "promotion"
    ]
    assert len(promo_ms) == tiers, (len(promo_ms), tiers)
    summary = bridge.close()
    return {
        "metric": "joins_admitted_per_sec",
        "value": round(n_joins / dt, 1),
        "unit": "joins/s",
        "n0": n0,
        "tiers": tiers,
        "n_top": n_top,
        "n_live": summary["n_live"],
        "joins_total": n_joins,
        "dropped": led["shed"] + led["deferred"],  # certified 0 above
        "promotions": tiers,
        "promotion_wall_ms": [round(ms, 1) for ms in promo_ms],
        "promotion_wall_ms_mean": round(sum(promo_ms) / len(promo_ms), 1),
        "batches": summary["batches"],
        "ticks": summary["ticks"],
        "wall_s": round(dt, 2),
        "engine": "sparse-elastic",
    }


def _measure_load(producers: int = 32, n_members: int = 1024) -> dict:
    """The ``--load [producers] [n]`` rung: the seeded multi-producer wire
    harness (serve/load.py) — honest + adversarial loopback-TCP producers
    with connection churn against one live bounded-queue session. The row
    is the harness's own ``kind="load"`` audit row (events/s, backpressure
    pauses, rejections, conservation verdicts), so wire-rate regressions
    read directly against the offline and replay rungs in
    bench_history.jsonl."""
    import asyncio

    from scalecube_cluster_tpu.serve.load import run_load

    res = asyncio.run(
        run_load(
            n=n_members,
            slot_budget=_rung_slot_budget(n_members),
            producers=producers,
            adversarial=max(producers // 4, 5),
            events_per_producer=400,
            max_pending=4096,
            churn_every=100,
        )
    )
    return res["row"]


def _measure_tracer_overhead(
    n_members: int = 4096, chunk: int = 48, reps: int = 4
) -> dict:
    """The ``--tracer-overhead [n]`` rung: the same sparse trajectory run
    tracer-off and tracer-on (flight recorder armed via ``trace_capacity``),
    reporting the on/off wall-time ratio next to the tracer-on throughput.

    The timeline carries real churn (kills, a restart, 5% loss) so the
    recorder's emission paths — probe episodes, suspicions, verdicts —
    actually fire; a quiet cluster would measure only the ring's fixed
    per-tick cost. The per-shard recorder in the SPMD engine reuses the
    exact same emission code on shard-local shapes (parallel/spmd.py §9.5),
    so this single-device ratio is the per-member cost model for both.
    """
    from scalecube_cluster_tpu.obs.trace import ring_overflow
    from scalecube_cluster_tpu.sim.faults import FaultPlan
    from scalecube_cluster_tpu.sim.schedule import ScheduleBuilder
    from scalecube_cluster_tpu.sim.sparse import (
        SparseParams,
        init_sparse_full_view,
        run_sparse_ticks,
    )

    params = SparseParams.for_n(n_members)
    sched = (
        ScheduleBuilder(n_members)
        .add_segment(0, FaultPlan.uniform(loss_percent=5.0))
        .kill(3, 7)
        .kill(5, n_members // 2)
        .restart(25, 7)
        .build()
    )
    capacity = 1 << 16

    def timed(trace_capacity: int):
        state = init_sparse_full_view(
            n_members, params.slot_budget, trace_capacity=trace_capacity
        )
        # Warmup: compile + steady state, same discipline as the other rungs.
        state, _ = run_sparse_ticks(params, state, sched, chunk, collect=False)
        int(state.view_T[0, 0])
        t0 = time.perf_counter()
        for _ in range(reps):
            state, _ = run_sparse_ticks(
                params, state, sched, chunk, collect=False
            )
            int(state.view_T[0, 0])
        return time.perf_counter() - t0, state

    dt_off, _ = timed(0)
    dt_on, traced = timed(capacity)
    value = n_members * (reps * chunk / dt_on)
    return {
        "metric": "member_gossip_rounds_per_sec",
        "value": round(value, 1),
        "unit": "member·rounds/s",
        "vs_baseline": round(value / BASELINE_MEMBER_ROUNDS_PER_SEC, 3),
        "ns_per_member": _ns_per_member(value),
        "tracer_overhead": round(dt_on / dt_off, 4),
        "trace_capacity": capacity,
        "events_recorded": int(traced.trace.cursor),
        "trace_overflow": int(ring_overflow(traced.trace)),
        "n_members": n_members,
        "engine": "sparse-traced",
    }


def _measure(engine: str, n_members: int, slot_budget: int | None = None) -> dict:
    """Run one benchmark config in-process and return the result dict."""
    if engine in ("sparse", "sparse-pallas"):
        value = _measure_sparse(
            n_members,
            pallas=(engine == "sparse-pallas"),
            slot_budget=slot_budget,
        )
    else:
        value = _measure_dense(n_members, pallas=(engine == "dense"))
    out = {
        "metric": "member_gossip_rounds_per_sec",
        "value": round(value, 1),
        "unit": "member·rounds/s",
        "vs_baseline": round(value / BASELINE_MEMBER_ROUNDS_PER_SEC, 3),
        "ns_per_member": _ns_per_member(value),
        "n_members": n_members,
        "engine": engine,
    }
    if slot_budget:
        out["slot_budget"] = slot_budget
    return out


def _telemetry(n_members: int = 4096, out: str = "telemetry.jsonl") -> None:
    """Flight-recorder run: one collected sparse run exporting the full
    counter timeline totals plus detection-latency histograms as
    schema-versioned JSONL (obs/export.py), and a Prometheus snapshot
    alongside (``<out>.prom``). This is the ``--telemetry`` mode — the
    headline bench path keeps ``collect=False`` and pays nothing.
    """
    from scalecube_cluster_tpu.obs.counters import SHARED_COUNTERS, SIM_ONLY_COUNTERS
    from scalecube_cluster_tpu.obs.export import (
        append_jsonl,
        make_row,
        run_metadata,
        write_prometheus,
    )
    from scalecube_cluster_tpu.obs.latency import (
        detection_latencies,
        latency_histogram,
    )
    from scalecube_cluster_tpu.obs.profiling import trace_scope
    from scalecube_cluster_tpu.sim.faults import FaultPlan
    from scalecube_cluster_tpu.sim.sparse import (
        SparseParams,
        init_sparse_full_view,
        kill_sparse,
        run_sparse_chunked,
    )

    params = SparseParams.for_n(n_members, in_scan_writeback=False)
    state = kill_sparse(
        init_sparse_full_view(n_members, params.slot_budget, record_latency=True), 7
    )
    plan = FaultPlan.uniform(loss_percent=5.0)
    ticks = 240
    with trace_scope("bench/telemetry_run"):
        state, traces = run_sparse_chunked(
            params, state, plan, ticks, chunk=48, collect=True
        )
    meta = run_metadata(n=n_members, slot_budget=params.slot_budget, seed=0)
    totals = {
        k: int(traces[k].sum())
        for k in SHARED_COUNTERS + SIM_ONLY_COUNTERS
        if k in traces
    }
    rows = [make_row("counters", {**totals, "n_ticks": ticks}, meta)]
    lat = detection_latencies(state, {7: 0})
    for event, arr in (
        ("first_suspect", lat["suspect_latency"]),
        ("first_dead", lat["dead_latency"]),
    ):
        rows.append(
            make_row("latency_histogram", {"event": event, **latency_histogram(arr)}, meta)
        )
    append_jsonl(out, rows)
    write_prometheus(out + ".prom", rows)
    print(json.dumps({"telemetry": out, "rows": len(rows), "ticks": ticks, "n": n_members}))


def _probe_once() -> str | None:
    """One backend check: tiny op in a subprocess under a deadline.

    Returns None when the backend is usable, else the failure description.
    """
    code = (
        "import jax, jax.numpy as jnp, numpy as np;"
        "x = jnp.arange(64, dtype=jnp.int32);"
        "print(int(np.asarray(x.sum())))"
    )
    try:
        res = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True,
            text=True,
            timeout=PROBE_DEADLINE_S,
        )
        if res.returncode == 0 and res.stdout.strip().endswith("2016"):
            return None
        return f"probe rc={res.returncode}: {res.stderr.strip()[-300:]}"
    except subprocess.TimeoutExpired:
        return f"probe timed out after {PROBE_DEADLINE_S}s"


def _record_probe_attempt(
    attempt: int, err: str | None, elapsed_s: float, extra: dict | None = None
) -> None:
    """Append one probe-attempt outcome to artifacts/bench_history.jsonl.

    Outage rounds used to burn their probe budget invisibly (BENCH_r05: 8
    attempts × 120 s before the 0.0 row); now every attempt leaves a
    schema row, so the history shows WHEN the tunnel was down and how much
    budget each round spent discovering it. ``extra`` merges scenario
    context into the attempt row — the serve rung stamps its ingest→verdict
    SLO percentiles here so the probe history carries the serving-latency
    trend, not just up/down; any attempt whose extra carries a
    ``member_rounds_per_sec`` throughput gets ``ns_per_member`` stamped
    alongside automatically, so the per-member cost trend lives in the
    same timeline. Best-effort: a read-only or missing artifacts/ dir must
    never break the bench's one-JSON-line contract.
    """
    try:
        from scalecube_cluster_tpu.obs.export import append_jsonl, make_row, run_metadata

        path = os.path.join(
            os.path.dirname(os.path.abspath(__file__)), "artifacts", "bench_history.jsonl"
        )
        payload = {
            "attempt": attempt,
            "ok": err is None,
            "detail": (err or "")[-300:],
            "elapsed_s": round(elapsed_s, 1),
            "budget_s": PROBE_DEADLINE_S,
            **(extra or {}),
        }
        if "member_rounds_per_sec" in payload:
            payload.setdefault(
                "ns_per_member", _ns_per_member(payload["member_rounds_per_sec"])
            )
        row = make_row("bench_probe", payload, run_metadata())
        append_jsonl(path, [row])
    except Exception:
        pass


def _self_evidence() -> dict:
    """Last self-measured result + provenance, for outage-round error JSON.

    Round-2 verdict: an outage round reported value 0.0 with no way to tell
    "measured then tunnel died" from "never measured". PERF_SELF.json is the
    committed raw artifact of the most recent self-run; surface it (plus the
    commit hash) whenever the driver's own run can't measure.
    """
    out = {}
    try:
        res = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            capture_output=True,
            text=True,
            timeout=10,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
        if res.returncode == 0:
            out["commit"] = res.stdout.strip()
    except Exception:
        pass
    try:
        path = os.path.join(os.path.dirname(os.path.abspath(__file__)), "PERF_SELF.json")
        with open(path) as fh:
            out["last_self_measured"] = json.load(fh)
    except Exception:
        pass
    return out


def _run_child(engine: str, n: int, slot_budget: int | None) -> tuple[dict | None, str]:
    """One measured config in a subprocess with a hard deadline.

    A fresh process per config also isolates backend state, so a wedged TPU
    dispatch can only cost this config, not the whole benchmark. Returns
    ``(result, failure_detail)``.
    """
    tag = f"{engine} n={n} S={slot_budget or 'default'}"
    try:
        res = subprocess.run(
            [sys.executable, __file__, "--child", engine, str(n), str(slot_budget or 0)],
            capture_output=True,
            text=True,
            timeout=CHILD_DEADLINE_S,
        )
    except subprocess.TimeoutExpired:
        return None, f"{tag}: timed out after {CHILD_DEADLINE_S}s"
    if res.returncode != 0:
        return None, f"{tag}: rc={res.returncode}: {res.stderr.strip()[-300:]}"
    for line in reversed(res.stdout.strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line), ""
            except json.JSONDecodeError:
                return None, f"{tag}: unparseable stdout"
    return None, f"{tag}: no JSON line in stdout"


def main() -> None:
    """Probe-then-measure, persisting until TOTAL_BUDGET_S is spent.

    Round-2 verdict weak#1: the old probe gave up after ~615 s with ~585 s
    of budget unspent, and the tunnel has been observed to recover minutes
    after a long wedge. Now probing and ladder descent interleave until the
    budget line: every probe success starts a ladder pass; every failure
    backs off briefly and re-probes, as long as enough budget remains for a
    probe (plus, ideally, a child).
    """
    t_start = time.monotonic()

    def budget_left() -> float:
        return TOTAL_BUDGET_S - (time.monotonic() - t_start)

    result = None
    err = "never probed"
    last_fail = ""
    probes = 0
    while result is None and budget_left() > PROBE_DEADLINE_S + 5:
        t_probe = time.monotonic()
        err = _probe_once()
        probes += 1
        _record_probe_attempt(probes, err, time.monotonic() - t_probe)
        if err is not None:
            time.sleep(min(15, max(1, budget_left() - PROBE_DEADLINE_S)))
            continue
        children = 0
        for engine, n, slot_budget in LADDER:
            if budget_left() < 30:
                break
            children += 1
            result, fail = _run_child(engine, n, slot_budget)
            if result is not None:
                break
            last_fail = fail
        if result is None:
            if children == 0:
                err = "probe ok but budget exhausted before any config ran"
            else:
                err = f"all {children} attempted configs failed ({last_fail})"
            break
    if result is None:
        result = {
            "metric": "member_gossip_rounds_per_sec",
            "value": 0.0,
            "unit": "member·rounds/s",
            "vs_baseline": 0.0,
            "ns_per_member": None,
            "error": f"{err} (probe attempts: {probes})",
            **_self_evidence(),
        }
    else:
        result.update(_self_evidence())
    # Schema-stamped export row (obs/export.py) — same single-JSON-line
    # contract, now versioned and deterministic-ordered. The driver process
    # never imports jax, so run_metadata's platform detection stays passive.
    from scalecube_cluster_tpu.obs.export import jsonl_line, make_row, run_metadata

    print(jsonl_line(make_row("bench", result, run_metadata())), flush=True)


if __name__ == "__main__":
    if len(sys.argv) in (4, 5) and sys.argv[1] == "--child":
        # Persistent compilation cache: the supervisor's earlier on-chip
        # bench run (tools/tpu_supervisor.sh step 2) populates .jax_cache
        # with these exact programs, so the driver's own run skips the
        # 20-40 s cold compiles and fits its deadline more easily.
        try:
            from scalecube_cluster_tpu.utils.jaxcache import enable_repo_jax_cache

            enable_repo_jax_cache()
        except Exception:
            pass
        s_arg = int(sys.argv[4]) if len(sys.argv) == 5 else 0
        print(json.dumps(_measure(sys.argv[2], int(sys.argv[3]), s_arg or None)))
    elif len(sys.argv) >= 3 and sys.argv[1] == "--ensemble":
        try:
            from scalecube_cluster_tpu.utils.jaxcache import enable_repo_jax_cache

            enable_repo_jax_cache()
        except Exception:
            pass
        from scalecube_cluster_tpu.obs.export import jsonl_line, make_row, run_metadata

        b_count = int(sys.argv[2])
        n_arg = int(sys.argv[3]) if len(sys.argv) > 3 else 1024
        out = _measure_ensemble(b_count, n_arg)
        print(
            jsonl_line(make_row("bench_ensemble", out, run_metadata(seed=0))),
            flush=True,
        )
    elif len(sys.argv) >= 2 and sys.argv[1] == "--rapid":
        try:
            from scalecube_cluster_tpu.utils.jaxcache import enable_repo_jax_cache

            enable_repo_jax_cache()
        except Exception:
            pass
        from scalecube_cluster_tpu.obs.export import jsonl_line, make_row, run_metadata

        n_arg = int(sys.argv[2]) if len(sys.argv) > 2 else 1024
        out = _measure_rapid(n_arg)
        print(
            jsonl_line(make_row("bench_rapid", out, run_metadata(seed=0))),
            flush=True,
        )
    elif len(sys.argv) >= 2 and sys.argv[1] == "--geo":
        try:
            from scalecube_cluster_tpu.utils.jaxcache import enable_repo_jax_cache

            enable_repo_jax_cache()
        except Exception:
            pass
        from scalecube_cluster_tpu.obs.export import (
            append_jsonl,
            jsonl_line,
            make_row,
            run_metadata,
        )

        n_arg = int(sys.argv[2]) if len(sys.argv) > 2 else 1024
        # One recorded backend probe first (the ladder driver's discipline:
        # outage budget must leave evidence in bench_history.jsonl).
        t_probe = time.monotonic()
        probe_err = _probe_once()
        _record_probe_attempt(1, probe_err, time.monotonic() - t_probe)
        if probe_err is not None:
            row = make_row(
                "bench_geo",
                {"error": probe_err, "n_members": n_arg, **_self_evidence()},
                run_metadata(seed=0),
            )
        else:
            out = _measure_geo(n_arg)
            row = make_row("bench_geo", out, run_metadata(seed=0))
            _record_probe_attempt(
                2,
                None,
                time.monotonic() - t_probe,
                extra={
                    "scenario": "geo",
                    "engine": out["engine"],
                    "n_members": n_arg,
                    "member_rounds_per_sec": out["value"],
                    "geo_overhead": out["geo_overhead"],
                },
            )
        try:
            append_jsonl(
                os.path.join(
                    os.path.dirname(os.path.abspath(__file__)),
                    "artifacts",
                    "bench_history.jsonl",
                ),
                [row],
            )
        except Exception:
            pass
        print(jsonl_line(row), flush=True)
    elif len(sys.argv) >= 3 and sys.argv[1] == "--shard-map":
        pos = [a for a in sys.argv[2:] if not a.startswith("--")]
        use_pallas = "--pallas" in sys.argv[2:]
        d_arg = int(pos[0])
        n_arg = int(pos[1]) if len(pos) > 1 else 32768
        # CPU-only boxes (JAX_PLATFORMS=cpu): force d virtual host devices
        # BEFORE the first jax import, same mechanism as tests/conftest.py.
        if os.environ.get("JAX_PLATFORMS", "") == "cpu":
            flag = "--xla_force_host_platform_device_count"
            if flag not in os.environ.get("XLA_FLAGS", ""):
                os.environ["XLA_FLAGS"] = (
                    os.environ.get("XLA_FLAGS", "") + f" {flag}={d_arg}"
                ).strip()
        try:
            from scalecube_cluster_tpu.utils.jaxcache import enable_repo_jax_cache

            enable_repo_jax_cache()
        except Exception:
            pass
        from scalecube_cluster_tpu.obs.export import (
            append_jsonl,
            jsonl_line,
            make_row,
            run_metadata,
        )

        # One recorded backend probe first (the ladder driver's discipline:
        # outage budget must leave evidence in bench_history.jsonl).
        t_probe = time.monotonic()
        probe_err = _probe_once()
        _record_probe_attempt(1, probe_err, time.monotonic() - t_probe)
        if probe_err is not None:
            row = make_row(
                "bench_shard_map",
                {"error": probe_err, "shards": d_arg, **_self_evidence()},
                run_metadata(seed=0),
            )
        else:
            out = _measure_shard_map(d_arg, n_arg, pallas=use_pallas)
            row = make_row("bench_shard_map", out, run_metadata(seed=0))
            # Stamp throughput + ns/member onto a probe-attempt row too
            # (same discipline as --serve's SLO percentiles): the probe
            # history is the long-lived per-round record, so the
            # per-member cost trend shows up in the same timeline as
            # outages.
            _record_probe_attempt(
                2,
                None,
                time.monotonic() - t_probe,
                extra={
                    "scenario": "shard_map",
                    "engine": out["engine"],
                    "shards": d_arg,
                    "n_members": n_arg,
                    "member_rounds_per_sec": out["value"],
                },
            )
        try:
            append_jsonl(
                os.path.join(
                    os.path.dirname(os.path.abspath(__file__)),
                    "artifacts",
                    "bench_history.jsonl",
                ),
                [row],
            )
        except Exception:
            pass
        print(jsonl_line(row), flush=True)
    elif len(sys.argv) >= 2 and sys.argv[1] == "--persistent-ksweep":
        try:
            from scalecube_cluster_tpu.utils.jaxcache import enable_repo_jax_cache

            enable_repo_jax_cache()
        except Exception:
            pass
        from scalecube_cluster_tpu.obs.export import (
            append_jsonl,
            jsonl_line,
            make_row,
            run_metadata,
        )

        pos = [a for a in sys.argv[2:] if not a.startswith("--")]
        n_arg = int(pos[0]) if pos else 4096
        k_arg = int(pos[1]) if len(pos) > 1 else 8
        # One recorded backend probe first (same discipline as --shard-map:
        # outage budget must leave evidence in bench_history.jsonl).
        t_probe = time.monotonic()
        probe_err = _probe_once()
        _record_probe_attempt(1, probe_err, time.monotonic() - t_probe)
        if probe_err is not None:
            rows = [
                make_row(
                    "bench_persistent",
                    {
                        "error": probe_err,
                        "n_members": n_arg,
                        "k_max": k_arg,
                        **_self_evidence(),
                    },
                    run_metadata(seed=0),
                )
            ]
        else:
            sweep = _measure_persistent_ksweep(n_arg, k_max=k_arg)
            rows = [
                make_row("bench_persistent", r, run_metadata(seed=0))
                for r in sweep
            ]
            best = max(sweep, key=lambda r: r["value"])
            _record_probe_attempt(
                2,
                None,
                time.monotonic() - t_probe,
                extra={
                    "scenario": "persistent_ksweep",
                    "n_members": n_arg,
                    "k": best["k"],
                    "k_max": k_arg,
                    "member_rounds_per_sec": best["value"],
                },
            )
        try:
            append_jsonl(
                os.path.join(
                    os.path.dirname(os.path.abspath(__file__)),
                    "artifacts",
                    "bench_history.jsonl",
                ),
                rows,
            )
        except Exception:
            pass
        for row in rows:
            print(jsonl_line(row), flush=True)
    elif len(sys.argv) >= 2 and sys.argv[1] == "--serve":
        try:
            from scalecube_cluster_tpu.utils.jaxcache import enable_repo_jax_cache

            enable_repo_jax_cache()
        except Exception:
            pass
        from scalecube_cluster_tpu.obs.export import (
            append_jsonl,
            jsonl_line,
            make_row,
            run_metadata,
        )

        n_arg = int(sys.argv[2]) if len(sys.argv) > 2 else 4096
        # One recorded backend probe first (same discipline as --shard-map:
        # outage budget must leave evidence in bench_history.jsonl).
        t_probe = time.monotonic()
        probe_err = _probe_once()
        _record_probe_attempt(1, probe_err, time.monotonic() - t_probe)
        if probe_err is not None:
            row = make_row(
                "serve",
                {"error": probe_err, "n_members": n_arg, **_self_evidence()},
                run_metadata(seed=0),
            )
        else:
            row = _measure_serve(n_arg)
            # Stamp the session's ingest→verdict SLO percentiles onto a
            # probe-attempt row too: the probe history is the long-lived
            # per-round record, so serving-latency regressions show up in
            # the same timeline as outages.
            _record_probe_attempt(
                2,
                None,
                time.monotonic() - t_probe,
                extra={
                    "scenario": "serve",
                    "n_members": n_arg,
                    **{
                        k: row[k]
                        for k in (
                            "latency_ms_p50",
                            "latency_ms_p95",
                            "latency_ms_p99",
                            "latency_ms_mean",
                        )
                        if k in row
                    },
                },
            )
        try:
            append_jsonl(
                os.path.join(
                    os.path.dirname(os.path.abspath(__file__)),
                    "artifacts",
                    "bench_history.jsonl",
                ),
                [row],
            )
        except Exception:
            pass
        print(jsonl_line(row), flush=True)
    elif len(sys.argv) >= 2 and sys.argv[1] == "--tracer-overhead":
        try:
            from scalecube_cluster_tpu.utils.jaxcache import enable_repo_jax_cache

            enable_repo_jax_cache()
        except Exception:
            pass
        from scalecube_cluster_tpu.obs.export import (
            append_jsonl,
            jsonl_line,
            make_row,
            run_metadata,
        )

        n_arg = int(sys.argv[2]) if len(sys.argv) > 2 else 4096
        # One recorded backend probe first (same discipline as --shard-map:
        # outage budget must leave evidence in bench_history.jsonl).
        t_probe = time.monotonic()
        probe_err = _probe_once()
        _record_probe_attempt(1, probe_err, time.monotonic() - t_probe)
        if probe_err is not None:
            row = make_row(
                "bench_tracer",
                {"error": probe_err, "n_members": n_arg, **_self_evidence()},
                run_metadata(seed=0),
            )
        else:
            out = _measure_tracer_overhead(n_arg)
            row = make_row("bench_tracer", out, run_metadata(seed=0))
            # The probe history is the long-lived per-round record: the
            # recorder's cost trend belongs in the same timeline as outages
            # and throughput, so a tracer regression reads off one file.
            _record_probe_attempt(
                2,
                None,
                time.monotonic() - t_probe,
                extra={
                    "scenario": "tracer_overhead",
                    "n_members": n_arg,
                    "tracer_overhead": out["tracer_overhead"],
                    "ns_per_member": out["ns_per_member"],
                },
            )
        try:
            append_jsonl(
                os.path.join(
                    os.path.dirname(os.path.abspath(__file__)),
                    "artifacts",
                    "bench_history.jsonl",
                ),
                [row],
            )
        except Exception:
            pass
        print(jsonl_line(row), flush=True)
    elif len(sys.argv) >= 2 and sys.argv[1] == "--grow":
        try:
            from scalecube_cluster_tpu.utils.jaxcache import enable_repo_jax_cache

            enable_repo_jax_cache()
        except Exception:
            pass
        from scalecube_cluster_tpu.obs.export import (
            append_jsonl,
            jsonl_line,
            make_row,
            run_metadata,
        )

        n_arg = int(sys.argv[2]) if len(sys.argv) > 2 else 64
        tiers_arg = int(sys.argv[3]) if len(sys.argv) > 3 else 2
        # One recorded backend probe first (the ladder driver's discipline:
        # outage budget must leave evidence in bench_history.jsonl).
        t_probe = time.monotonic()
        probe_err = _probe_once()
        _record_probe_attempt(1, probe_err, time.monotonic() - t_probe)
        if probe_err is not None:
            row = make_row(
                "grow",
                {"error": probe_err, "n0": n_arg, "tiers": tiers_arg,
                 **_self_evidence()},
                run_metadata(seed=0),
            )
        else:
            out = _measure_grow(n_arg, tiers_arg)
            row = make_row("grow", out, run_metadata(seed=0))
            # The probe history is the long-lived per-round record: the
            # admission-rate and promotion-cost trends belong in the same
            # timeline as outages, so elasticity regressions read off one
            # file.
            _record_probe_attempt(
                2,
                None,
                time.monotonic() - t_probe,
                extra={
                    "scenario": "grow",
                    "n0": n_arg,
                    "tiers": tiers_arg,
                    "n_top": out["n_top"],
                    "joins_per_sec": out["value"],
                    "promotion_wall_ms_mean": out["promotion_wall_ms_mean"],
                },
            )
        try:
            append_jsonl(
                os.path.join(
                    os.path.dirname(os.path.abspath(__file__)),
                    "artifacts",
                    "bench_history.jsonl",
                ),
                [row],
            )
        except Exception:
            pass
        print(jsonl_line(row), flush=True)
    elif len(sys.argv) >= 2 and sys.argv[1] == "--load":
        try:
            from scalecube_cluster_tpu.utils.jaxcache import enable_repo_jax_cache

            enable_repo_jax_cache()
        except Exception:
            pass
        from scalecube_cluster_tpu.obs.export import (
            append_jsonl,
            jsonl_line,
            make_row,
            run_metadata,
        )

        producers_arg = int(sys.argv[2]) if len(sys.argv) > 2 else 32
        n_arg = int(sys.argv[3]) if len(sys.argv) > 3 else 1024
        t_probe = time.monotonic()
        probe_err = _probe_once()
        _record_probe_attempt(1, probe_err, time.monotonic() - t_probe)
        if probe_err is not None:
            row = make_row(
                "load",
                {"error": probe_err, "n_members": n_arg, **_self_evidence()},
                run_metadata(seed=0),
            )
        else:
            row = _measure_load(producers_arg, n_arg)
            # The probe history is the long-lived per-round record: stamp
            # the wire-rate SLO + verdicts there too, same discipline as
            # the --serve rung's latency stamp.
            _record_probe_attempt(
                2,
                None,
                time.monotonic() - t_probe,
                extra={
                    "scenario": "load",
                    "n_members": n_arg,
                    **{
                        k: row[k]
                        for k in (
                            "events_per_sec",
                            "backpressure_pauses",
                            "rejected",
                            "latency_ms_p95",
                            "conservation_ok",
                            "bounded_ok",
                        )
                        if k in row
                    },
                },
            )
        try:
            append_jsonl(
                os.path.join(
                    os.path.dirname(os.path.abspath(__file__)),
                    "artifacts",
                    "bench_history.jsonl",
                ),
                [row],
            )
        except Exception:
            pass
        print(jsonl_line(row), flush=True)
    elif len(sys.argv) >= 2 and sys.argv[1] == "--fleet":
        try:
            from scalecube_cluster_tpu.utils.jaxcache import enable_repo_jax_cache

            enable_repo_jax_cache()
        except Exception:
            pass
        from scalecube_cluster_tpu.obs.export import (
            append_jsonl,
            jsonl_line,
            make_row,
            run_metadata,
        )

        fleet_arg = int(sys.argv[2]) if len(sys.argv) > 2 else 4
        n_arg = int(sys.argv[3]) if len(sys.argv) > 3 else 1024
        t_probe = time.monotonic()
        probe_err = _probe_once()
        _record_probe_attempt(1, probe_err, time.monotonic() - t_probe)
        if probe_err is not None:
            row = make_row(
                "fleet",
                {"error": probe_err, "n_members": n_arg, **_self_evidence()},
                run_metadata(seed=0),
            )
        else:
            row = _measure_fleet(fleet_arg, n_arg)
            _record_probe_attempt(
                2,
                None,
                time.monotonic() - t_probe,
                extra={
                    "scenario": "fleet",
                    "n_members": n_arg,
                    "fleet_size": fleet_arg,
                    **{
                        k: row[k]
                        for k in (
                            "tenant_member_rounds_per_sec",
                            "events_per_sec",
                            "fleet_wall_s",
                            "solo_wall_s",
                            "fleet_vs_solo_ratio",
                        )
                        if k in row
                    },
                },
            )
        try:
            append_jsonl(
                os.path.join(
                    os.path.dirname(os.path.abspath(__file__)),
                    "artifacts",
                    "bench_history.jsonl",
                ),
                [row],
            )
        except Exception:
            pass
        print(jsonl_line(row), flush=True)
    elif len(sys.argv) >= 2 and sys.argv[1] == "--telemetry":
        _telemetry(
            n_members=int(sys.argv[3]) if len(sys.argv) > 3 else 4096,
            out=sys.argv[2] if len(sys.argv) > 2 else "telemetry.jsonl",
        )
    else:
        os.environ.setdefault("JAX_TRACEBACK_FILTERING", "off")
        main()
