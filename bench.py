"""Headline benchmark: member-gossip-rounds per second on one chip.

Simulates a dense SWIM cluster (sim/) at the largest member count that fits
single-chip HBM dense, under LAN protocol ratios with 5% packet loss — the
BASELINE.json "1k-member SWIM sim, 5% packet loss + suspicion" config scaled
up. One tick advances every member one gossip round (plus the FD/SYNC work on
their cadence), so throughput = n_members × ticks/sec, measured against the
driver's north-star 1M member-gossip-rounds/sec (BASELINE.json north_star).

Hardened per VERDICT.md round-1 item 1: this script ALWAYS prints exactly one
JSON line on stdout, no matter what the TPU tunnel does.

- A tiny probe op with a hard deadline runs first, retried with backoff; if
  the backend never comes up, the JSON line carries an ``"error"`` field.
- Each measured config runs in a subprocess with its own deadline, so a
  mid-dispatch hang (the round-1 failure mode: BENCH_r01.json rc=1, later
  re-runs hanging >4 min) is converted into a fallback down an n-ladder.
- Timing syncs via a host fetch of the tick counter — jax.block_until_ready
  can report ready prematurely over this box's tunneled-TPU transport.

Usage: ``python bench.py`` (driver mode — one JSON line) or
``python bench.py --child <n> <pallas>`` (internal single-config worker).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

BASELINE_MEMBER_ROUNDS_PER_SEC = 1_000_000.0
#: Largest-first ladder of member counts; first one that lands a number wins.
N_LADDER = (10240, 4096, 1024)
PROBE_DEADLINE_S = 120
PROBE_RETRIES = 3
CHILD_DEADLINE_S = 420
#: Hard budget on total wall time before the JSON line must be out — stops
#: starting new children once exceeded, so a wedged backend can't push the
#: guaranteed output past the driver's patience (probe + first child worst
#: case still fits well under it).
TOTAL_BUDGET_S = 1200


def _measure(n_members: int, pallas: bool, chunk: int = 40, reps: int = 4) -> dict:
    """Run the sim benchmark in-process and return the result dict."""
    from scalecube_cluster_tpu.sim import FaultPlan, SimParams, init_full_view, run_ticks
    from scalecube_cluster_tpu.sim.state import seeds_mask

    params = SimParams.from_cluster_config(n_members)
    if pallas:
        import dataclasses

        params = dataclasses.replace(params, pallas_delivery=True)
    state = init_full_view(n_members)
    plan = FaultPlan.clean(n_members).with_loss(5.0)
    seeds = seeds_mask(n_members, [0, 1])

    # Warmup: compile + reach protocol steady state. int() is the host fetch
    # that actually synchronizes (see module docstring).
    state, _ = run_ticks(params, state, plan, seeds, chunk, collect=False)
    int(state.tick)

    t0 = time.perf_counter()
    for _ in range(reps):
        state, _ = run_ticks(params, state, plan, seeds, chunk, collect=False)
        int(state.tick)
    dt = time.perf_counter() - t0

    value = n_members * (reps * chunk / dt)
    return {
        "metric": "member_gossip_rounds_per_sec",
        "value": round(value, 1),
        "unit": "member·rounds/s",
        "vs_baseline": round(value / BASELINE_MEMBER_ROUNDS_PER_SEC, 3),
        "n_members": n_members,
        "pallas": pallas,
    }


def _probe() -> str | None:
    """Fail-fast backend check: tiny op in a subprocess under a deadline.

    Returns None when the backend is usable, else the failure description.
    """
    code = (
        "import jax, jax.numpy as jnp, numpy as np;"
        "x = jnp.arange(64, dtype=jnp.int32);"
        "print(int(np.asarray(x.sum())))"
    )
    err = "probe never ran"
    for attempt in range(PROBE_RETRIES):
        try:
            res = subprocess.run(
                [sys.executable, "-c", code],
                capture_output=True,
                text=True,
                timeout=PROBE_DEADLINE_S,
            )
            if res.returncode == 0 and res.stdout.strip().endswith("2016"):
                return None
            err = f"probe rc={res.returncode}: {res.stderr.strip()[-300:]}"
        except subprocess.TimeoutExpired:
            err = f"probe timed out after {PROBE_DEADLINE_S}s"
        if attempt + 1 < PROBE_RETRIES:
            time.sleep(2**attempt)
    return err


def _run_child(n: int, pallas: bool) -> tuple[dict | None, str]:
    """One measured config in a subprocess with a hard deadline.

    A fresh process per config also isolates backend state, so a wedged TPU
    dispatch can only cost this config, not the whole benchmark. Returns
    ``(result, failure_detail)``.
    """
    tag = f"n={n} pallas={int(pallas)}"
    try:
        res = subprocess.run(
            [sys.executable, __file__, "--child", str(n), str(int(pallas))],
            capture_output=True,
            text=True,
            timeout=CHILD_DEADLINE_S,
        )
    except subprocess.TimeoutExpired:
        return None, f"{tag}: timed out after {CHILD_DEADLINE_S}s"
    if res.returncode != 0:
        return None, f"{tag}: rc={res.returncode}: {res.stderr.strip()[-300:]}"
    for line in reversed(res.stdout.strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line), ""
            except json.JSONDecodeError:
                return None, f"{tag}: unparseable stdout"
    return None, f"{tag}: no JSON line in stdout"


def main() -> None:
    t_start = time.monotonic()
    result = None
    err = _probe()
    last_fail = ""
    out_of_budget = False
    if err is None:
        for n in N_LADDER:
            for pallas in (True, False):
                if time.monotonic() - t_start > TOTAL_BUDGET_S:
                    out_of_budget = True
                    last_fail = f"budget {TOTAL_BUDGET_S}s exhausted; " + last_fail
                    break
                result, fail = _run_child(n, pallas)
                if result is not None:
                    break
                last_fail = fail
            if result is not None or out_of_budget:
                break
        if result is None:
            err = f"all benchmark configs failed ({last_fail})"
    if result is None:
        result = {
            "metric": "member_gossip_rounds_per_sec",
            "value": 0.0,
            "unit": "member·rounds/s",
            "vs_baseline": 0.0,
            "error": err,
        }
    print(json.dumps(result), flush=True)


if __name__ == "__main__":
    if len(sys.argv) == 4 and sys.argv[1] == "--child":
        print(json.dumps(_measure(int(sys.argv[2]), bool(int(sys.argv[3])))))
    else:
        os.environ.setdefault("JAX_TRACEBACK_FILTERING", "off")
        main()
